"""Shared-memory data plane for intra-host worlds.

When every rank lives on one host (one process per TPU chip is the
normal deployment shape), tensors should move through RAM, not through
the loopback TCP stack. The reference does exactly this where it
matters most: ``MPIHierarchicalAllgather`` stages node-local data in an
``MPI_Win_allocate_shared`` window and lets ranks memcpy in and out of
it (reference: horovod/common/ops/mpi_operations.cc:179-329). This
backend is the standalone rendering of that idea: one POSIX shared
memory segment per world, negotiated through the existing TCP control
plane, carrying every collective's payload at memcpy speed.

Layout is fixed per segment generation so concurrent ops can never
alias each other across a cycle boundary:

    [ slot 0 | slot 1 | ... | slot N-1 | out region (N slots wide) ]

with every slot ``stride`` bytes (page-padded to the largest negotiated
payload so far; the segment re-establishes and grows when an op
outgrows it). Invariants that make the sync rounds safe:

  * a rank writes ONLY its own slot, and only at the start of its own
    execute — which is provably after it finished reading the previous
    op's result;
  * the out region is written only between a completed world gather
    (all ranks wrote their slots + stopped reading the previous op)
    and the round that releases readers. Writers per path: the
    coordinator alone on the small-op single-round path; each rank's
    DISJOINT 1/N slice between the two barriers of the large-op
    slice-parallel path; local roots on the hierarchical path;
  * results are copied out of the segment before the op returns, so
    user-visible outputs never alias shared pages.

The segment file is unlinked immediately after the establishment
rendezvous (the mappings keep the memory alive), so no /dev/shm litter
survives a crash. Establishment failure on any rank is agreed
world-wide (``controller.agree``) and degrades every rank to the
socket backend together — same pattern as the ring data plane
(ops/ring.py).
"""

from __future__ import annotations

import json
import mmap
import os
from typing import Optional, Tuple

import numpy as np

from horovod_tpu import native as _native
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import wire_dtype as _wd
from horovod_tpu.common.controller import _my_hostname
from horovod_tpu.common.message import Response, ResponseType
from horovod_tpu.common.status import Status
from horovod_tpu.common.timeline import (
    ACT_MEMCPY_IN_FUSION_BUFFER, ACT_MEMCPY_OUT_FUSION_BUFFER,
)
from horovod_tpu.ops.backend import CollectiveBackend
from horovod_tpu.ops.socket_ops import (
    _allgather_layout, _np_from_bytes, _pack_flat, _pack_fused,
    _restore, _to_numpy, _unpack_allgather, _unpack_fused,
)

_PAGE = 4096
# Same-host allreduces at or above this size split the reduction work
# across ranks (slice-parallel sum) instead of summing on the
# coordinator; below it the single-round coordinator sum wins on
# latency.
_PARALLEL_SUM_BYTES = 1 << 20


def _pad(nbytes: int) -> int:
    return -(-max(nbytes, 1) // _PAGE) * _PAGE


class ShmBackend(CollectiveBackend):
    name = "shm"

    def __init__(self, controller, fallback: CollectiveBackend,
                 config=None, secret: bytes = b""):
        self._ctl = controller
        self._fallback = fallback
        self._map: Optional[mmap.mmap] = None
        self._stride = 0
        self._gen = 0
        self._dead = False
        self._opt_in = True if config is None else config.shm_enabled
        self._zero_copy = True if config is None else config.zero_copy
        # Tenant sub-worlds (common/tenancy.py) namespace their
        # segments: two worlds hosted by ONE process (same pid, same
        # generation counter) must never collide on a segment path —
        # the old pid+gen name did exactly that.
        self._world_id = 0 if config is None \
            else int(getattr(config, "world_id", 0))
        # Persistent pack buffer (common/arena.py): fused steady steps
        # re-pack into the same memory instead of allocating per step.
        # Safe here because every shm result is copied OUT of the
        # segment/accumulators before entries see it.
        from horovod_tpu.common.arena import FusionArena
        self._arena = FusionArena() if self._zero_copy else None
        self._m_regrows = None  # set by attach_metrics
        self._m_twolevel = None
        # Two-level cross-host ring among LOCAL ROOTS (ops/ring.py
        # subset establishment): lazy, once, world-agreed — same
        # pattern as the socket backend's flat ring.
        self._secret = secret
        self._roots_ring = None
        self._roots_ring_tried = False
        self._roots_ok = False  # world-identical after first establish
        # int8 error-feedback residuals for the cross-host leg — the
        # same rank-local compensation the socket plane keeps, so the
        # numerics do not silently depend on the transport.
        self._ef = _wd.ErrorFeedback()
        self._ring_hb = None
        if config is not None and config.heartbeat_timeout_s > 0:
            self._ring_hb = (config.heartbeat_timeout_s,
                             config.heartbeat_interval_s)

    def attach_metrics(self, registry) -> None:
        super().attach_metrics(registry)
        # Each regrow re-establishes the segment world-wide — a climbing
        # count means payload sizes keep outgrowing the stride.
        self._m_regrows = registry.counter(
            "hvd_shm_segment_regrows_total",
            "shared-memory segment re-establishments")
        self._m_twolevel = registry.counter(
            "hvd_ops_twolevel_total",
            "allreduce batches carried by the two-level plane "
            "(intra-host shm reduce, cross-host ring among local "
            "roots, intra-host shm broadcast)")

    def enabled(self, entries, response) -> bool:
        """World-consistent by construction: topology is identical on
        every rank, the coordinator's ALG_* stamp rides the broadcast
        response, and anything that can genuinely fail per host
        (segment creation, /dev/shm itself) is decided inside
        establishment by a world-wide agree() vote."""
        t = getattr(self._ctl, "topology", None)
        if not (self._opt_in and not self._dead and t is not None
                and t.size > 1):
            return False
        if response is not None \
                and response.response_type == ResponseType.ALLREDUCE \
                and response.algorithm in (_wd.ALG_STAR, _wd.ALG_RING,
                                           _wd.ALG_ICI):
            # A stamped FLAT algorithm belongs to the socket plane —
            # declining here is what makes the coordinator's verdict
            # (and the autotuner's exploration) actually select it.
            # ALG_ICI counts as flat too: its intra-slice leg runs on
            # the device mesh before the cycle and its cross-slice leg
            # is the (compressed) socket star/ring.
            return False
        if t.local_size == t.size:
            return True  # same-host world: every collective
        if not (response is not None
                and response.response_type == ResponseType.ALLREDUCE):
            return False
        if response.algorithm == _wd.ALG_TWOLEVEL:
            # The two-level plane serves ANY multi-host world (an
            # all-solo-hosts topology degenerates to the roots ring —
            # still hierarchical bookkeeping, no local legs).
            return True
        # Default routing: the hierarchical local-reduce -> cross ->
        # local-broadcast path, worthwhile when at least one host runs
        # several ranks.
        return max(t.local_sizes) > 1

    @property
    def _hier(self) -> bool:
        t = self._ctl.topology
        return t.local_size < t.size

    # -- segment lifecycle -------------------------------------------------

    def _segment_for(self, nbytes: int) -> Optional[Tuple[mmap.mmap, int]]:
        """Return (mmap, stride) able to hold one ``nbytes`` payload per
        LOCAL slot, (re)establishing through the control plane when the
        current segment is too small. All ranks call this at the same
        negotiated response position with the same ``nbytes``.

        One segment per HOST, created by that host's local root and
        advertised through a hostname-keyed path map broadcast by the
        coordinator (a same-host world is the one-host special case).
        """
        stride = _pad(nbytes)
        solo = self._hier and self._ctl.topology.local_size == 1
        if self._stride >= stride and (self._map is not None or solo):
            return self._map, self._stride
        ctl = self._ctl
        t = ctl.topology
        # Grow generously so streams of slightly-increasing sizes don't
        # re-establish every op.
        stride = _pad(max(stride, 2 * self._stride))
        total = stride * t.local_size * 2
        self._gen += 1
        if self._m_regrows is not None:
            self._m_regrows.inc()
        my_host = _my_hostname()
        new_map = None
        path = ""
        ok = True
        if t.local_rank == 0 and not solo:
            path = (f"/dev/shm/hvdtpu-{os.getpid()}"
                    f"-w{self._world_id:x}-{self._gen}")
            try:
                fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL,
                             0o600)
                try:
                    os.ftruncate(fd, total)
                    new_map = mmap.mmap(fd, total)
                finally:
                    os.close(fd)
            except OSError as e:
                hlog.warning(f"shm segment create failed: {e!r}",
                             rank=ctl.rank)
                path, ok = "", False
            payload = json.dumps(
                {"host": my_host, "path": path, "total": total}).encode()
        else:
            payload = b""
        gathered = ctl.gather_data(payload)
        if gathered is not None:  # coordinator
            host_map = {}
            for data in gathered:
                if len(data):
                    info = json.loads(bytes(data).decode())
                    host_map[info["host"]] = (info["path"],
                                              info["total"])
            blob = ctl.broadcast_data(json.dumps(host_map).encode())
        else:
            blob = ctl.broadcast_data(None)
        if new_map is None and not solo:
            # non-creators open their host's segment (solo hier hosts
            # need no segment: there is nobody to share with)
            host_map = json.loads(bytes(blob).decode())
            entry = host_map.get(my_host, ("", 0))
            if entry[0]:
                try:
                    fd = os.open(entry[0], os.O_RDWR)
                    try:
                        new_map = mmap.mmap(fd, entry[1])
                    finally:
                        os.close(fd)
                except OSError as e:
                    hlog.warning(
                        f"shm segment open failed: {e!r}", rank=ctl.rank)
                    ok = False
            else:
                ok = False
        agreed = ctl.agree(ok)
        if path:
            # Every local rank holds a mapping (or we are tearing
            # down); the name can go away now — crash-safe cleanup.
            try:
                os.unlink(path)
            except OSError:
                pass
        if not agreed:
            for m in (new_map, self._map):
                if m is not None:
                    try:
                        m.close()
                    except (BufferError, ValueError):
                        pass
            self._map = None
            self._dead = True
            hlog.warning("shm data plane unavailable; falling back to "
                         "the socket backend", rank=ctl.rank)
            return None
        old = self._map
        self._map = new_map
        self._stride = stride
        if old is not None:
            # Rendezvous above was a barrier: nobody still reads old.
            try:
                old.close()
            except (BufferError, ValueError):
                pass
        return self._map, self._stride

    def _world_barrier(self) -> None:
        # the socket backend's empty gather/broadcast round IS a world
        # barrier; one implementation serves both uses
        self._fallback.execute_barrier((), None)

    def _view(self, offset: int, dtype, count: int) -> np.ndarray:
        return np.frombuffer(self._map, dtype=dtype, count=count,
                             offset=offset)

    def _sum_slots(self, acc: np.ndarray, ranks, stride: int, dtype,
                   count: int, lo: int = 0) -> None:
        """acc += sum of slot[r][lo:lo+len(acc)] for r in ranks (native
        kernel with numpy fallback) — the one accumulation loop every
        reduction path shares."""
        for r in ranks:
            src = self._view(r * stride, dtype, count)[lo:lo + acc.size]
            if not _native.sum_into(acc, src):
                acc += src

    def close(self) -> None:
        if self._roots_ring is not None:
            try:
                self._roots_ring.close()
            except Exception:
                pass
            self._roots_ring = None
        if self._map is not None:
            try:
                self._map.close()
            except (BufferError, ValueError):
                pass
            self._map = None

    # -- collectives ---------------------------------------------------------

    def execute_allreduce(self, entries, response: Response) -> Status:
        ctl = self._ctl
        arrays = [_to_numpy(e.tensor) for e in entries]
        dtype = arrays[0].dtype
        names = [e.tensor_name for e in entries]
        multi = len(entries) > 1  # single-tensor pack is a view
        with self.activity(names, ACT_MEMCPY_IN_FUSION_BUFFER, multi):
            # Arena-safe: every shm result is copied out of the
            # segment before entries see it, so outputs never alias
            # the pack buffer.
            fused, _ = _pack_fused(arrays, response, self._arena)
        if fused.size == 0:
            # Nothing to move; every rank short-circuits identically
            # (sizes are negotiated), so no control rounds are owed.
            _unpack_fused(entries, arrays, np.empty(0, dtype=dtype),
                          response)
            return Status.OK()
        seg = self._segment_for(fused.nbytes)
        if seg is None:
            return self._fallback.execute_allreduce(entries, response)
        _, stride = seg
        if self._hier:
            result = self._hier_allreduce(fused, dtype, stride,
                                          response)
        elif fused.nbytes >= _PARALLEL_SUM_BYTES:
            result = self._parallel_sum_allreduce(fused, dtype, stride)
        else:
            out_off = ctl.size * stride
            if ctl.is_coordinator:
                ctl.gather_data(b"")  # all slots written
                out = self._view(out_off, dtype, fused.size)
                out[:] = fused
                self._sum_slots(out, range(1, ctl.size), stride, dtype,
                                fused.size)
                ctl.broadcast_data(b"")
                result = out.copy()
            else:
                slot = self._view(ctl.rank * stride, dtype, fused.size)
                slot[:] = fused
                ctl.gather_data(b"")
                ctl.broadcast_data(None)
                result = self._view(out_off, dtype, fused.size).copy()
        with self.activity(names, ACT_MEMCPY_OUT_FUSION_BUFFER, multi):
            _unpack_fused(entries, arrays, result, response)
        return Status.OK()

    def _parallel_sum_allreduce(self, fused: np.ndarray, dtype,
                                stride: int) -> np.ndarray:
        """Large-payload same-host allreduce with the REDUCTION work
        split across ranks: every rank writes its slot, then sums its
        1/N slice of all slots into the out region (the reduce-scatter
        + all-gather of a ring, rendered on shared memory). Costs one
        extra sync round vs the coordinator-sum path but divides the
        sum's memory-bandwidth load N ways — the same reason the
        reference's hierarchical ops spread work over ranks."""
        ctl = self._ctl
        size = ctl.size
        out_off = size * stride
        slot = self._view(ctl.rank * stride, dtype, fused.size)
        slot[:] = fused
        self._world_barrier()  # round A: all slots written
        # exact integer split: contiguous, gap-free, overlap-free
        lo = ctl.rank * fused.size // size
        hi = (ctl.rank + 1) * fused.size // size
        if hi > lo:
            out = self._view(out_off, dtype, fused.size)
            acc = out[lo:hi]
            acc[:] = self._view(0, dtype, fused.size)[lo:hi]
            self._sum_slots(acc, range(1, size), stride, dtype,
                            fused.size, lo=lo)
        self._world_barrier()  # round B: every slice summed
        return self._view(out_off, dtype, fused.size).copy()

    def _roots_ring_for(self):
        """Cross-host ring among LOCAL ROOTS — the two-level plane's
        middle leg. Established lazily, ONCE, at a world-consistent
        response position (every rank runs the rendezvous control
        rounds; only roots open links). Non-roots get None even on
        success, so one extra one-time agree() round publishes the
        verdict to them — ``self._roots_ok`` is world-identical after
        the first call."""
        if not self._roots_ring_tried:
            self._roots_ring_tried = True
            roots = list(self._ctl.topology.local_roots)
            from horovod_tpu.ops import ring as _ring
            self._roots_ring = _ring.establish(
                self._ctl, self._secret, hb=self._ring_hb,
                members=roots)
            member = self._ctl.rank in roots
            self._roots_ok = self._ctl.agree(
                (self._roots_ring is not None) if member else True)
        return self._roots_ring

    def _cross_exchange_star(self, acc, dtype, wire: int,
                             count: int, key: tuple):
        """Cross-host leg, star shape: roots funnel their host sums
        through the coordinator (compressed at the negotiated wire
        dtype), everyone else rides the rounds with empty payloads so
        the protocol stays size-independent. Returns the f32 world sum
        on roots, None elsewhere."""
        from horovod_tpu.ops import socket_ops as _sops
        ctl = self._ctl
        t = ctl.topology
        lr = t.local_rank
        wire_nbytes = _wd.compressed_nbytes(
            wire, count, dtype.itemsize) if wire else 0
        if lr == 0:
            # ONE shared compress-leg implementation with the socket
            # plane (cast/quantize + error feedback + saved/ratio
            # metrics), so the transports can never drift on numerics
            # or accounting.
            payload = _sops.compress_send_payload(
                acc, wire, self._ef, key) if wire else acc
        else:
            payload = b""
        gathered = ctl.gather_data(payload)  # round 2a
        # Root membership comes from the topology, not payload lengths,
        # so the protocol is size-independent.
        roots = set(t.local_roots)
        if gathered is not None:  # coordinator (always a local root)
            peers = [gathered[r] for r in range(1, ctl.size)
                     if r in roots]
            if wire:
                from horovod_tpu.common.network import as_byte_view
                out_buf = _wd.reduce_wire(payload, peers, wire,
                                          dtype, count)
                blob = as_byte_view(out_buf)
                total = _wd.decompress(out_buf, wire, dtype, count)
            else:
                total = payload  # acc, fresh
                for p in peers:
                    src = np.frombuffer(p, dtype=dtype)
                    if not _native.sum_into(total, src):
                        total += src
                blob = memoryview(total).cast("B")
            payloads = [blob if r in roots else b""
                        for r in range(ctl.size)]
            payloads[0] = b""  # our own copy is ``total`` already
            ctl.scatter_data(payloads)  # round 2b
            return total
        if self._zero_copy:
            # Roots receive the world sum straight into a fresh array;
            # non-roots' empty slice costs nothing.
            if wire == _wd.WIRE_INT8:
                flat = np.empty(wire_nbytes if lr == 0 else 0,
                                np.uint8)
            elif wire:
                flat = np.empty(count if lr == 0 else 0,
                                _wd.wire_np_dtype(wire))
            else:
                flat = np.empty(count if lr == 0 else 0, dtype)
            ctl.scatter_data_into(None, flat)  # round 2b
            if lr != 0:
                return None
            return _wd.decompress(flat, wire, dtype, count) \
                if wire else flat
        data = ctl.scatter_data(None)  # round 2b
        if lr != 0:
            return None
        if wire:
            return _wd.decompress(data, wire, dtype, count)
        return _np_from_bytes(data, dtype)

    def _hier_allreduce(self, fused: np.ndarray, dtype,
                        stride: int, response: Response) -> np.ndarray:
        """Multi-host allreduce: local shm reduce -> cross-host
        exchange among LOCAL ROOTS only -> local shm broadcast. The
        exact decomposition of the reference's
        ``NCCLHierarchicalAllreduce`` (nccl_operations.cc:167-372:
        intra-node reduce, inter-node exchange on one participant per
        node, intra-node broadcast), with cross-host bytes cut from
        N*S to K*S for K hosts — and cut AGAIN by the negotiated wire
        dtype, applied only to the cross-host leg (intra-host legs
        move through RAM, where a cast costs more than it saves).

        The cross leg has two shapes, selected by the coordinator's
        ALG_* stamp: the classic star through rank 0 (default), or —
        ``ALG_TWOLEVEL`` — a reduce-scatter/allgather ring among the
        local roots (ops/ring.py subset ring), whose per-root wire
        bytes are 2·S·(K-1)/K instead of the star root's 2·S·(K-1).

        Control rounds, identical on every rank:
          1. barrier — all local slots written;
          2. cross leg (star: gather+scatter rounds; ring: root-to-
             root links only — no world rounds);
          3. barrier — out regions written; locals read.
        """
        ctl = self._ctl
        t = ctl.topology
        lr, ls = t.local_rank, t.local_size
        out_off = ls * stride

        if lr != 0:
            slot = self._view(lr * stride, dtype, fused.size)
            slot[:] = fused
        self._world_barrier()  # round 1: every host's slots complete

        acc = None
        if lr == 0:
            acc = np.array(fused, dtype=dtype, copy=True)
            self._sum_slots(acc, range(1, ls), stride, dtype,
                            fused.size)

        wire = response.wire_dtype \
            if _wd.is_floating(dtype) else _wd.WIRE_NONE
        twolevel = response.algorithm == _wd.ALG_TWOLEVEL
        if twolevel:
            # Every rank reaches this establishment point for the same
            # response, so the rendezvous rounds stay world-aligned;
            # an unestablishable ring degrades every rank to the star
            # exchange together (world-agreed vote).
            ring = self._roots_ring_for()
            twolevel = self._roots_ok
        if twolevel:
            if self._m_twolevel is not None:
                self._m_twolevel.inc()
            result = None
            if lr == 0:
                wire = _wd.ring_wire(wire)
                if wire:
                    from horovod_tpu.ops import socket_ops as _sops
                    wbuf = _sops.compress_send_payload(acc, wire)
                    ring.allreduce_(wbuf)
                    result = _wd.decompress(wbuf, wire, dtype,
                                            fused.size)
                else:
                    result = ring.allreduce_(acc)
        else:
            result = self._cross_exchange_star(
                acc, dtype, wire, fused.size,
                tuple(response.tensor_names))

        if lr == 0 and ls > 1:
            # solo hosts have no readers — skip the out-region copy
            out = self._view(out_off, dtype, fused.size)
            out[:] = result
        self._world_barrier()  # round 3: out regions complete
        if lr != 0:
            result = self._view(out_off, dtype, fused.size).copy()
        return result

    def execute_allgather(self, entries, response: Response) -> Status:
        ctl = self._ctl
        arrays = [np.ascontiguousarray(_to_numpy(e.tensor))
                  for e in entries]
        names = [e.tensor_name for e in entries]
        comp, rank_counts = _allgather_layout(entries, arrays, response,
                                              ctl.size)
        itemsize = arrays[0].dtype.itemsize
        seg = self._segment_for(max(rank_counts) * itemsize)
        if seg is None:
            return self._fallback.execute_allgather(entries, response)
        _, stride = seg
        out_off = ctl.size * stride
        total_elems = sum(rank_counts)
        multi = len(entries) > 1
        with self.activity(names, ACT_MEMCPY_IN_FUSION_BUFFER, multi):
            packed = _pack_flat(arrays, self._arena)
        dtype = packed.dtype
        if ctl.is_coordinator:
            ctl.gather_data(b"")
            out = self._view(out_off, dtype, total_elems)
            pos = 0
            for r in range(ctl.size):
                n = rank_counts[r]
                if r == 0:
                    out[pos:pos + n] = packed
                else:
                    out[pos:pos + n] = self._view(r * stride, dtype, n)
                pos += n
            ctl.broadcast_data(b"")
            result = out.copy()
        else:
            slot = self._view(ctl.rank * stride, dtype, packed.size)
            slot[:] = packed
            ctl.gather_data(b"")
            ctl.broadcast_data(None)
            result = self._view(out_off, dtype, total_elems).copy()
        with self.activity(names, ACT_MEMCPY_OUT_FUSION_BUFFER, multi):
            _unpack_allgather(entries, arrays, result, comp,
                              rank_counts)
        return Status.OK()

    def execute_broadcast(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        orig = _to_numpy(entry.tensor)
        arr = np.ascontiguousarray(orig)
        seg = self._segment_for(arr.nbytes)
        if seg is None:
            return self._fallback.execute_broadcast(entries, response)
        _, stride = seg
        out_off = ctl.size * stride
        root = entry.root_rank
        if ctl.rank == root and not ctl.is_coordinator:
            slot = self._view(ctl.rank * stride, arr.dtype, arr.size)
            slot[:] = arr.reshape(-1)
        if ctl.is_coordinator:
            ctl.gather_data(b"")
            out = self._view(out_off, arr.dtype, arr.size)
            if root == 0:
                out[:] = arr.reshape(-1)
            else:
                out[:] = self._view(root * stride, arr.dtype, arr.size)
            ctl.broadcast_data(b"")
        else:
            ctl.gather_data(b"")
            ctl.broadcast_data(None)
        result = self._view(out_off, arr.dtype, arr.size).copy()
        entry.output = _restore(entry, result.reshape(orig.shape))
        return Status.OK()

    def execute_alltoall(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        seg = self._segment_for(arr.nbytes)
        if seg is None:
            return self._fallback.execute_alltoall(entries, response)
        _, stride = seg
        size = ctl.size
        out_off = size * stride
        per_elems = (arr.shape[0] // size) * (
            int(np.prod(arr.shape[1:], dtype=np.int64))
            if arr.ndim > 1 else 1)
        if ctl.is_coordinator:
            ctl.gather_data(b"")
            flat0 = arr.reshape(-1)
            # destination d's block lands at out_off + d*stride, source
            # blocks concatenated in rank order.
            for d in range(size):
                dst = self._view(out_off + d * stride, arr.dtype,
                                 size * per_elems)
                for s in range(size):
                    blk = (flat0[d * per_elems:(d + 1) * per_elems]
                           if s == 0 else
                           self._view(s * stride, arr.dtype,
                                      arr.size)[d * per_elems:
                                                (d + 1) * per_elems])
                    dst[s * per_elems:(s + 1) * per_elems] = blk
            ctl.broadcast_data(b"")
        else:
            slot = self._view(ctl.rank * stride, arr.dtype, arr.size)
            slot[:] = arr.reshape(-1)
            ctl.gather_data(b"")
            ctl.broadcast_data(None)
        result = self._view(out_off + ctl.rank * stride, arr.dtype,
                            size * per_elems).copy()
        entry.output = _restore(entry, result.reshape(arr.shape))
        return Status.OK()

    def execute_reducescatter(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        if response.prescale_factor != 1.0:
            arr = arr * np.asarray(response.prescale_factor, arr.dtype)
        seg = self._segment_for(arr.nbytes)
        if seg is None:
            return self._fallback.execute_reducescatter(entries, response)
        _, stride = seg
        size = ctl.size
        out_off = size * stride
        per_rank = arr.shape[0] // size
        per_elems = per_rank * (int(np.prod(arr.shape[1:],
                                            dtype=np.int64))
                                if arr.ndim > 1 else 1)
        if ctl.is_coordinator:
            ctl.gather_data(b"")
            out = self._view(out_off, arr.dtype, arr.size)
            out[:] = arr.reshape(-1)
            self._sum_slots(out, range(1, size), stride, arr.dtype,
                            arr.size)
            ctl.broadcast_data(b"")
        else:
            slot = self._view(ctl.rank * stride, arr.dtype, arr.size)
            slot[:] = arr.reshape(-1)
            ctl.gather_data(b"")
            ctl.broadcast_data(None)
        result = self._view(out_off + ctl.rank * per_elems *
                            arr.dtype.itemsize, arr.dtype,
                            per_elems).copy()
        result = result.reshape((per_rank,) + arr.shape[1:])
        if response.postscale_factor != 1.0:
            result = result * np.asarray(response.postscale_factor,
                                         arr.dtype)
        entry.output = _restore(entry, result)
        return Status.OK()

    def execute_barrier(self, entries, response: Response) -> Status:
        # A barrier moves no payload; the socket backend's tiny
        # gather/broadcast round IS the barrier.
        return self._fallback.execute_barrier(entries, response)
