"""TCP socket collective backend — the universal host fallback.

Role-equivalent of the reference's MPI CPU ops
(reference: horovod/common/ops/mpi_operations.cc — ``MPIAllreduce``
25-84, ``MPIAllgather`` 95-173, ``MPIBroadcast`` 334-358), which are the
always-enabled last resort in the op priority list. A TPU host has no
MPI; this backend runs the same collectives over the controller's
persistent TCP channels with a star topology (gather → combine at rank 0
→ broadcast/scatter).

Payloads are numpy buffers; jax arrays are staged through host memory
here, exactly like the reference's *CudaOnCPU staging path
(reference: horovod/torch/mpi_ops_v2.cc:78-111). The XLA mesh backend
(xla_ops.py) outranks this one whenever a multi-process JAX world
exists, keeping the data plane on ICI/DCN.

Fused allreduce packs all entries into one contiguous buffer before the
wire round-trip — the fusion-buffer pack/unpack of the reference
(reference: ops/collective_operations.cc:35-63) — so a fused batch costs
one gather+broadcast regardless of tensor count.
"""

from __future__ import annotations

from typing import List

import numpy as np

from horovod_tpu import native as _native
from horovod_tpu.common import wire_dtype as _wd
from horovod_tpu.common.arena import FusionArena, concat_into
from horovod_tpu.common.controller import Controller
from horovod_tpu.common.message import (
    Response, datatype_to_numpy_dtype, numpy_dtype_to_datatype,
)
from horovod_tpu.common.metrics import NOOP_METRIC
from horovod_tpu.common.status import Status
from horovod_tpu.common.timeline import (
    ACT_MEMCPY_IN_FUSION_BUFFER, ACT_MEMCPY_OUT_FUSION_BUFFER,
)
from horovod_tpu.ops.backend import CollectiveBackend

# Fallback-copy observability (hvd_data_copies_total, shared with the
# runtime's counter by registry name-memoization): every defensive
# byte-object copy the zero-copy plane exists to delete ticks it, so
# "is the zero-copy path engaged" is one metrics read. NOOP when
# metrics are off/unattached.
_COPY_METRIC = NOOP_METRIC

# Wire-compression observability, shared by name with the runtime's
# counters: bytes this rank did NOT put on the wire thanks to the
# negotiated wire dtype, and the per-op compression ratio.
_SAVED_METRIC = NOOP_METRIC
_RATIO_METRIC = NOOP_METRIC


def _to_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    return np.asarray(tensor)


def record_compression(src_nbytes: int, wire_nbytes: int) -> None:
    """THE one wire-compression accounting site (saved bytes +
    ratio): every compress leg — the backends via
    compress_send_payload, the runtime's spec/native steady packs —
    ticks through here, so the metric semantics can never drift
    between planes."""
    _SAVED_METRIC.inc(max(0, src_nbytes - wire_nbytes))
    _RATIO_METRIC.observe(wire_nbytes / max(1, src_nbytes))


def compress_send_payload(arr: np.ndarray, wire: int, ef=None,
                          key: tuple = None,
                          out: np.ndarray = None) -> np.ndarray:
    """THE one compress-leg implementation every host plane shares:
    wire-cast (into ``out`` — an arena view — when given) or int8
    quantize with error feedback, plus the saved-bytes/ratio metrics.
    One call per payload per op, so the counters stay exact however
    many planes reuse it."""
    record_compression(
        arr.nbytes,
        _wd.compressed_nbytes(wire, arr.size, arr.dtype.itemsize))
    if wire == _wd.WIRE_INT8:
        if ef is not None:
            # Fused native pass: compensate + quantize + next-step
            # residual in one sweep (falls back to the classic
            # apply -> quantize -> update triple, bit-identically).
            return _wd.quantize_ef(arr, ef, key)
        return _wd.quantize(arr)
    if out is not None:
        _wd.cast_into(arr, out)
        return out
    return arr.astype(_wd.wire_np_dtype(wire))  # fresh + writable


def _np_from_bytes(data: bytes, dtype) -> np.ndarray:
    """Writable array over received bytes. A bare ``np.frombuffer`` over
    ``bytes`` is read-only and would poison outputs (callers expect
    writable tensors, like the reference's allocated outputs). This IS
    the defensive copy the zero-copy recv-into paths delete — counted,
    so the fallback tier is visible on the metrics plane."""
    _COPY_METRIC.inc()
    return np.frombuffer(bytearray(data), dtype=dtype)


def _restore(entry, host_result: np.ndarray):
    """Return the result in the entry's native flavor (jax in → jax out)."""
    if entry.context == "jax":
        import jax
        return jax.device_put(host_result)
    return host_result


def _pack_fused(arrays: List[np.ndarray], response: Response,
                arena: FusionArena = None):
    """Fusion-buffer pack shared by the host backends (reference:
    ops/collective_operations.cc:35-63). Returns (flat, fresh): ``fresh``
    is True when ``flat`` is known not to alias a caller tensor (safe to
    mutate in place). Single-tensor packs skip the copy, like the
    reference's MPI_IN_PLACE path (mpi_operations.cc:44-47). With an
    ``arena``, multi-tensor packs land in the persistent buffer
    instead of a per-step allocation — callers must then guarantee
    user-visible outputs never alias ``flat`` (see common/arena.py)."""
    dtype = arrays[0].dtype
    fresh = len(arrays) > 1
    flat = _pack_flat(arrays, arena)
    if response.prescale_factor != 1.0:
        if fresh and arena is not None and flat.flags.writeable:
            np.multiply(flat, np.asarray(response.prescale_factor,
                                         dtype), out=flat)
        else:
            flat = flat * np.asarray(response.prescale_factor, dtype)
        fresh = True
    return flat, fresh


def _allgather_layout(entries, arrays, response: Response, size: int):
    """Displacement math for a (possibly fused) allgather response
    (reference: AllgatherOp::AllocateOutput / SetEntryComponentOffsets,
    ops/collective_operations.cc:68-134). ``response.tensor_sizes`` is
    entry-major: sizes[ec * size + rc] = entry ec's dim-0 rows from
    rank rc. Returns (comp, rank_counts):
    comp[ec][rc] = elements entry ec contributes from rank rc;
    rank_counts[rc] = total elements in rank rc's packed block."""
    sizes = response.tensor_sizes
    comp = []
    for ec, a in enumerate(arrays):
        row = int(np.prod(a.shape[1:], dtype=np.int64)) \
            if a.ndim > 1 else 1
        comp.append([sizes[ec * size + rc] * row for rc in range(size)])
    rank_counts = [sum(comp[ec][rc] for ec in range(len(arrays)))
                   for rc in range(size)]
    return comp, rank_counts


def _pack_flat(arrays: List[np.ndarray],
               arena: FusionArena = None) -> np.ndarray:
    """Flatten + concatenate same-dtype tensors into one fused buffer
    (the reference's MemcpyInFusionBuffer for allreduce,
    collective_operations.cc:35-63, and for allgather — entry order —
    collective_operations.cc:136-150): the native one-call pack when
    available, numpy concatenation otherwise. Single-tensor packs stay
    a view. With an ``arena`` (and uniform dtypes) the pack reuses the
    persistent buffer — the reference's long-lived fusion buffer —
    instead of allocating per step. The one helper both host planes'
    allreduce AND allgather pack paths share."""
    if len(arrays) == 1:
        return np.ascontiguousarray(arrays[0]).reshape(-1)
    flats = [np.ascontiguousarray(a).reshape(-1) for a in arrays]
    if arena is not None:
        dtype = flats[0].dtype
        if all(a.dtype == dtype for a in flats):
            total = sum(a.size for a in flats)
            dst = arena.typed(0, dtype, total)
            concat_into(flats, dst)
            return dst
    packed = _native.pack(flats)
    return packed if packed is not None else np.concatenate(flats)


def _unpack_allgather(entries, arrays, result: np.ndarray, comp,
                      rank_counts) -> None:
    """Per-entry unpack of the rank-major gathered buffer: entry ec's
    output is the concatenation over ranks of its component inside each
    rank's block (the reference's allgather MemcpyOutFusionBuffer,
    collective_operations.cc:152-168)."""
    size = len(rank_counts)
    rank_off = [0] * size
    for rc in range(1, size):
        rank_off[rc] = rank_off[rc - 1] + rank_counts[rc - 1]
    # entry_off[rc]: running offset of the NEXT entry's component
    # inside rank rc's block — O(entries x ranks) total, not O(E^2 N).
    entry_off = list(rank_off)
    for ec, (e, a) in enumerate(zip(entries, arrays)):
        parts = []
        for rc in range(size):
            off = entry_off[rc]
            parts.append(result[off:off + comp[ec][rc]])
            entry_off[rc] = off + comp[ec][rc]
        flat = parts[0] if size == 1 else np.concatenate(parts)
        total_rows = sum(comp[ec]) // (
            int(np.prod(a.shape[1:], dtype=np.int64)) if a.ndim > 1
            else 1)
        e.output = _restore(e, flat.reshape((total_rows,) + a.shape[1:]))


def _unpack_fused(entries, arrays, result: np.ndarray, response: Response):
    """Per-entry unpack of a fused result + postscale (the reference's
    MemcpyOutFusionBuffer, collective_operations.cc:35-63). ``result``
    must be safe for entries to alias (fresh or already copied)."""
    if response.postscale_factor != 1.0:
        factor = np.asarray(response.postscale_factor, result.dtype)
        if result.flags.writeable:
            # postscale (the averaging hot path) in place: every caller
            # hands a fresh buffer, so this saves a payload-size copy
            np.multiply(result, factor, out=result)
        else:
            result = result * factor
    offset = 0
    for e, a in zip(entries, arrays):
        n = a.size
        e.output = _restore(e, result[offset:offset + n].reshape(a.shape))
        offset += n


class SocketBackend(CollectiveBackend):
    name = "socket"

    # Metrics defaults for never-attached (metrics-off) backends.
    _m_star_ops = NOOP_METRIC
    _m_ring_ops = NOOP_METRIC
    _m_ring_link_bytes = None

    def __init__(self, controller: Controller, secret: bytes = b"",
                 config=None):
        from horovod_tpu.common.config import Config
        cfg = config or Config()
        self._ctl = controller
        self._secret = secret
        self._ring = None
        self._ring_tried = False
        self._ring_threshold = cfg.ring_threshold_bytes
        # Zero-copy plane (HOROVOD_TPU_ZERO_COPY): pack into the
        # persistent fusion arena, receive into preallocated arrays.
        # Off restores the PR 3 byte-copy paths verbatim (the
        # collective_bench A/B lever).
        self._zero_copy = cfg.zero_copy
        self._arena = FusionArena()         # send-side pack buffer
        self._gather_arena = FusionArena()  # coordinator peer scratch
        # Liveness deadline for the worker↔worker ring channels (same
        # knobs as the control plane; None when detection is disabled).
        self._ring_hb = ((cfg.heartbeat_timeout_s,
                          cfg.heartbeat_interval_s)
                         if cfg.heartbeat_timeout_s > 0 else None)
        # Wire-compression state: a dedicated arena for compressed
        # send payloads (the f32 pack arena keeps its layout) and the
        # int8 error-feedback residual store (rank-local by design —
        # each rank compensates its OWN quantization error).
        self._wire_arena = FusionArena()
        self._ef = _wd.ErrorFeedback()

    def enabled(self, entries, response) -> bool:
        return self._ctl.size > 1

    def attach_metrics(self, registry) -> None:
        super().attach_metrics(registry)
        # Which route the negotiated size picked — the live answer to
        # "are my payloads riding the ring or funneling through the
        # star?" (docs/metrics.md).
        self._m_star_ops = registry.counter(
            'hvd_socket_path_ops_total{path="star"}')
        self._m_ring_ops = registry.counter(
            'hvd_socket_path_ops_total{path="ring"}')
        self._m_ring_link_bytes = registry.counter(
            "hvd_ring_link_bytes_total",
            "bytes this rank shipped over its ring link")
        # Same counter object as the runtime's (registry memoizes by
        # name): the module-level hook lets _np_from_bytes count from
        # shared helpers without threading a backend through.
        global _COPY_METRIC, _SAVED_METRIC, _RATIO_METRIC
        _COPY_METRIC = registry.counter(
            "hvd_data_copies_total",
            "payload byte-object copies on fallback data paths "
            "(0 while the zero-copy plane is engaged)")
        from horovod_tpu.common.metrics import RATIO_BUCKETS
        _SAVED_METRIC = registry.counter(
            "hvd_wire_bytes_saved_total",
            "payload bytes kept OFF the wire by the negotiated "
            "wire dtype (uncompressed minus wire size, per send)")
        _RATIO_METRIC = registry.histogram(
            "hvd_compression_ratio",
            "wire bytes / uncompressed bytes per compressed payload",
            RATIO_BUCKETS)
        # The int8 codec's numpy fallback legs tick the same copy
        # counter from inside wire_dtype (the native codec ticks
        # nothing — that's the point).
        _wd.attach_copy_counter(_COPY_METRIC)

    def fused_cycle_reducible(self, nbytes: int) -> bool:
        """Star-bound batches (below the ring threshold) already move
        through the coordinator's channels — exactly what the
        speculative fused cycle inlines. Mirrors _ring_for's routing
        WITHOUT establishing the ring (a probe must stay passive)."""
        return self._ctl.size > 1 and (
            self._ring_threshold < 0 or nbytes < self._ring_threshold
            or self._ctl.size < 3)

    def close(self) -> None:
        if self._ring is not None:
            self._ring.close()
            self._ring = None

    def _ring_for(self, nbytes: int, algorithm: int = 0):
        """Ring data plane for large payloads: establish lazily, once,
        at a world-consistent response position (all ranks evaluate the
        same negotiated size against the same threshold — and the same
        coordinator-stamped ALG_* verdict). None => star. A stamped
        ALG_STAR/ALG_RING overrides the size heuristic; an
        unestablishable forced ring degrades to the star on every rank
        together (the establishment vote is world-agreed)."""
        if algorithm == _wd.ALG_STAR:
            return None
        # HOROVOD_TPU_RING_THRESHOLD=-1 is an explicit operator
        # opt-out (firewalled inter-rank dials, broken fabric): a
        # stamped ALG_RING must not override it with a surprise
        # rendezvous — the world degrades to the star together.
        forced = (algorithm == _wd.ALG_RING and self._ctl.size >= 2
                  and self._ring_threshold >= 0)
        if not forced and (
                self._ring_threshold < 0 or nbytes < self._ring_threshold
                or self._ctl.size < 3):
            return None
        if not self._ring_tried:
            self._ring_tried = True
            from horovod_tpu.ops import ring as _ring
            self._ring = _ring.establish(self._ctl, self._secret,
                                         hb=self._ring_hb)
            # Capture the rebindable metric hook once: a metrics-plane
            # re-registration between the None test and the use would
            # hand the ring a half-initialized counter.
            m_link = self._m_ring_link_bytes
            if self._ring is not None and m_link is not None:
                self._ring.m_link_bytes = m_link
        return self._ring

    # -- allreduce -------------------------------------------------------
    def execute_allreduce(self, entries, response: Response) -> Status:
        ctl = self._ctl
        arrays = [_to_numpy(e.tensor) for e in entries]
        dtype = arrays[0].dtype
        names = [e.tensor_name for e in entries]
        multi = len(entries) > 1  # single-tensor pack is a view
        nbytes = sum(a.nbytes for a in arrays)
        # Route BEFORE packing: large payloads ride the ring (every
        # rank computes the same negotiated size against the same
        # threshold AND the same coordinator-stamped algorithm, so the
        # path choice is world-consistent). Routing uses UNCOMPRESSED
        # bytes on purpose — the wire dtype must not flip the route.
        ring = self._ring_for(nbytes, response.algorithm)
        # Arena packing only for batches that actually stay off the
        # ring: the uncompressed ring mutates its buffer in place AND
        # returns it as the result, so a ring-bound pack must stay a
        # per-op buffer outputs may alias — a size heuristic alone is
        # not enough, because a stamped ALG_RING (the autotuner
        # exploring) forces small batches onto the ring too, and an
        # arena-aliased output is then silently overwritten by the
        # next op's pack.
        use_arena = self._zero_copy and ring is None
        with self.activity(names, ACT_MEMCPY_IN_FUSION_BUFFER, multi):
            fused, fresh = _pack_fused(
                arrays, response, self._arena if use_arena else None)
        (self._m_ring_ops if ring is not None
         else self._m_star_ops).inc()
        wire = response.wire_dtype
        if wire != _wd.WIRE_NONE:
            result = self._compressed_allreduce(fused, wire, ring,
                                                names)
            with self.activity(names, ACT_MEMCPY_OUT_FUSION_BUFFER,
                               multi):
                _unpack_fused(entries, arrays, result, response)
            return Status.OK()
        if ring is not None:
            # allreduce is not in-place at the API: never mutate a buffer
            # that may alias the caller's tensor.
            buf = fused if (fresh and fused.flags.writeable) \
                else fused.copy()
            result = ring.allreduce_(buf)
        elif self._zero_copy:
            # Zero-copy star: peers land in scratch views / fresh
            # arrays; no byte object is ever materialized.
            if ctl.is_coordinator:
                acc = np.array(fused, dtype=dtype, copy=True)
                outs = [None] * ctl.size
                for r in range(1, ctl.size):
                    outs[r] = self._gather_arena.typed(
                        (r - 1) * fused.nbytes, dtype, fused.size)
                ctl.gather_data_into(fused, outs)
                for r in range(1, ctl.size):
                    if not _native.sum_into(acc, outs[r]):
                        acc += outs[r]
                ctl.broadcast_data(acc)
                result = acc
            else:
                ctl.gather_data_into(fused, None)
                result = np.empty(fused.size, dtype)
                ctl.broadcast_data_into(None, result)
        else:
            gathered = ctl.gather_data(fused)
            if gathered is not None:  # coordinator
                # gathered[0] is our own fused view — sum into a fresh
                # buffer so the caller's tensor is never mutated.
                acc = np.array(fused, dtype=dtype, copy=True)
                for data in gathered[1:]:
                    src = np.frombuffer(data, dtype=dtype)
                    if not _native.sum_into(acc, src):
                        acc += src
                ctl.broadcast_data(acc)
                result = acc
            else:
                result = _np_from_bytes(ctl.broadcast_data(None), dtype)

        with self.activity(names, ACT_MEMCPY_OUT_FUSION_BUFFER, multi):
            _unpack_fused(entries, arrays, result, response)
        return Status.OK()

    def _compressed_allreduce(self, fused: np.ndarray, wire: int,
                              ring, names) -> np.ndarray:
        """Allreduce with the negotiated wire dtype applied to every
        wire leg: compress AFTER the (prescaled) fusion pack, move and
        reduce in the wire representation, decompress ONCE into a
        fresh full-precision result the unpack may alias. The verdict
        and the route are both world-identical (broadcast response +
        negotiated sizes), so every rank takes the same branch."""
        ctl = self._ctl
        src_dtype = fused.dtype
        count = fused.size
        if ring is not None:
            wire = _wd.ring_wire(wire)
        wire_nbytes = _wd.compressed_nbytes(wire, count,
                                            src_dtype.itemsize)

        if wire == _wd.WIRE_INT8:
            # Error feedback (Deep Gradient Compression): add last
            # step's quantization residual before quantizing, keep
            # this step's error for the next one. Rank-local state by
            # design — each rank compensates its own error.
            qbuf = compress_send_payload(fused, wire, self._ef,
                                         tuple(names))
            if ctl.is_coordinator:
                if self._zero_copy:
                    outs = [None] * ctl.size
                    for r in range(1, ctl.size):
                        outs[r] = self._gather_arena.typed(
                            (r - 1) * wire_nbytes, np.uint8,
                            wire_nbytes)
                    ctl.gather_data_into(qbuf, outs)
                    peers = outs[1:]
                else:
                    peers = ctl.gather_data(qbuf)[1:]
                out_buf = _wd.reduce_wire(qbuf, peers, wire,
                                          src_dtype, count)
                ctl.broadcast_data(out_buf)
                return _wd.dequantize(out_buf, src_dtype, count)
            if self._zero_copy:
                ctl.gather_data_into(qbuf, None)
                rbuf = np.empty(wire_nbytes, np.uint8)
                ctl.broadcast_data_into(None, rbuf)
            else:
                ctl.gather_data(qbuf)
                rbuf = ctl.broadcast_data(None)
            return _wd.dequantize(rbuf, src_dtype, count)

        # Cast wires (bf16/fp16): reduction happens IN the wire dtype
        # (native hvd_sum_into converts pairwise through f32), exactly
        # like the native steady coordinator — the Python and C legs
        # are numerically interchangeable. The wire arena is safe for
        # the ring leg too: the ring mutates the WIRE buffer in place,
        # but outputs alias only the fresh decompressed result.
        np_wire = _wd.wire_np_dtype(wire)
        warr = compress_send_payload(
            fused, wire,
            out=self._wire_arena.typed(0, np_wire, count)
            if self._zero_copy else None)
        if ring is not None:
            result_wire = ring.allreduce_(warr)
            return _wd.decompress(result_wire, wire, src_dtype, count)
        if ctl.is_coordinator:
            acc = np.array(warr, copy=True)
            if self._zero_copy:
                outs = [None] * ctl.size
                for r in range(1, ctl.size):
                    outs[r] = self._gather_arena.typed(
                        (r - 1) * wire_nbytes, np_wire, count)
                ctl.gather_data_into(warr, outs)
                peers = outs[1:]
            else:
                peers = ctl.gather_data(warr)[1:]
            _wd.reduce_wire(acc, peers, wire, src_dtype, count)
            ctl.broadcast_data(acc)
            return _wd.decompress(acc, wire, src_dtype, count)
        if self._zero_copy:
            ctl.gather_data_into(warr, None)
            rarr = np.empty(count, np_wire)
            ctl.broadcast_data_into(None, rarr)
        else:
            ctl.gather_data(warr)
            rarr = ctl.broadcast_data(None)
        return _wd.decompress(rarr, wire, src_dtype, count)

    # -- allgather (multi-entry: fused responses) ------------------------
    def execute_allgather(self, entries, response: Response) -> Status:
        ctl = self._ctl
        arrays = [np.ascontiguousarray(_to_numpy(e.tensor))
                  for e in entries]
        names = [e.tensor_name for e in entries]
        multi = len(entries) > 1  # single-tensor pack is a view
        comp, rank_counts = _allgather_layout(entries, arrays, response,
                                              ctl.size)
        with self.activity(names, ACT_MEMCPY_IN_FUSION_BUFFER, multi):
            packed = _pack_flat(
                arrays, self._arena if (self._zero_copy and multi)
                else None)
        wire = response.wire_dtype
        if wire != _wd.WIRE_NONE:
            result = self._compressed_allgather(packed, wire,
                                                rank_counts)
        elif self._zero_copy:
            # Gather straight into the rank-major result: peer r's
            # block IS result[off_r : off_r + n_r], so the gathered
            # world buffer is assembled with zero intermediate copies.
            total = sum(rank_counts)
            result = np.empty(total, packed.dtype)
            offs = [0] * ctl.size
            for r in range(1, ctl.size):
                offs[r] = offs[r - 1] + rank_counts[r - 1]
            if ctl.is_coordinator:
                outs = [None] * ctl.size
                for r in range(1, ctl.size):
                    outs[r] = result[offs[r]:offs[r] + rank_counts[r]]
                ctl.gather_data_into(packed, outs)
                result[:rank_counts[0]] = packed
                ctl.broadcast_data(result)
            else:
                ctl.gather_data_into(packed, None)
                ctl.broadcast_data_into(None, result)
        else:
            gathered = ctl.gather_data(packed)
            if gathered is not None:
                _COPY_METRIC.inc()  # world-blob join (fallback tier)
                blob = b"".join(gathered)
                result = _np_from_bytes(ctl.broadcast_data(blob),
                                        packed.dtype)
            else:
                result = _np_from_bytes(ctl.broadcast_data(None),
                                        packed.dtype)
        with self.activity(names, ACT_MEMCPY_OUT_FUSION_BUFFER, multi):
            _unpack_allgather(entries, arrays, result, comp,
                              rank_counts)
        return Status.OK()

    def _compressed_allgather(self, packed: np.ndarray, wire: int,
                              rank_counts) -> np.ndarray:
        """Allgather with the negotiated CAST wire on the world
        exchange: every rank ships its block at wire width, the
        gathered world blob moves and broadcasts at wire width, and
        each rank decompresses ONCE into the full-dtype result the
        unpack may alias. int8 never reaches here — the coordinator's
        verdict degrades it to bf16 (wire_dtype.allgather_wire)
        because a concatenated blob cannot carry per-rank scales."""
        ctl = self._ctl
        src_dtype = packed.dtype
        np_wire = _wd.wire_np_dtype(wire)
        total = sum(rank_counts)
        warr = compress_send_payload(
            packed, wire,
            out=self._wire_arena.typed(0, np_wire, packed.size)
            if self._zero_copy else None)
        if self._zero_copy:
            wres = np.empty(total, np_wire)
            offs = [0] * ctl.size
            for r in range(1, ctl.size):
                offs[r] = offs[r - 1] + rank_counts[r - 1]
            if ctl.is_coordinator:
                # Peers land straight in their rank-major windows of
                # the wire result; nothing is ever re-assembled.
                outs = [None] * ctl.size
                for r in range(1, ctl.size):
                    outs[r] = wres[offs[r]:offs[r] + rank_counts[r]]
                ctl.gather_data_into(warr, outs)
                wres[:rank_counts[0]] = warr
                ctl.broadcast_data(wres)
            else:
                ctl.gather_data_into(warr, None)
                ctl.broadcast_data_into(None, wres)
            return _wd.decompress(wres, wire, src_dtype, total)
        gathered = ctl.gather_data(warr)
        if gathered is not None:
            wres = np.empty(total, np_wire)
            pos = 0
            for r, g in enumerate(gathered):
                n = rank_counts[r]
                src = g if isinstance(g, np.ndarray) \
                    else np.frombuffer(g, np_wire, count=n)
                wres[pos:pos + n] = src
                pos += n
            _COPY_METRIC.inc()  # store-and-forward re-assembly
            ctl.broadcast_data(wres)
            return _wd.decompress(wres, wire, src_dtype, total)
        return _wd.decompress(
            _np_from_bytes(ctl.broadcast_data(None), np_wire),
            wire, src_dtype, total)

    # -- broadcast -------------------------------------------------------
    def execute_broadcast(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        orig = _to_numpy(entry.tensor)
        # ascontiguousarray promotes 0-d to (1,); keep the true shape —
        # broadcast is the one collective defined on scalars.
        arr = np.ascontiguousarray(orig)
        if self._zero_copy:
            if ctl.rank == entry.root_rank:
                # The payload ships straight from the tensor's memory;
                # the output is one fresh copy (never an alias of the
                # user's input).
                ctl.broadcast_data(arr, root_rank=entry.root_rank)
                result = np.array(arr, copy=True)
            else:
                flat = np.empty(arr.size, arr.dtype)
                ctl.broadcast_data_into(None, flat,
                                        root_rank=entry.root_rank)
                result = flat
            entry.output = _restore(entry,
                                    result.reshape(orig.shape))
            return Status.OK()
        if ctl.rank == entry.root_rank:
            _COPY_METRIC.inc()  # send-side tobytes (fallback tier)
            data = ctl.broadcast_data(arr.tobytes(),
                                      root_rank=entry.root_rank)
        else:
            data = ctl.broadcast_data(None, root_rank=entry.root_rank)
        result = _np_from_bytes(data, arr.dtype).reshape(orig.shape)
        entry.output = _restore(entry, result)
        return Status.OK()

    # -- alltoall (TPU-native extension) ---------------------------------
    def execute_alltoall(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        size = ctl.size
        if self._zero_copy:
            per_rank = arr.shape[0] // size
            if ctl.is_coordinator:
                outs = [None] * size
                for r in range(1, size):
                    outs[r] = self._gather_arena.typed(
                        (r - 1) * arr.nbytes, arr.dtype, arr.size)
                ctl.gather_data_into(arr, outs)
                mats = [arr] + [outs[r].reshape(arr.shape)
                                for r in range(1, size)]
                payloads = [np.concatenate(
                    [m[d * per_rank:(d + 1) * per_rank] for m in mats])
                    for d in range(size)]
                ctl.scatter_data_into(payloads, None)
                result = payloads[0]
            else:
                ctl.gather_data_into(arr, None)
                result = np.empty(arr.size, arr.dtype)
                ctl.scatter_data_into(None, result)
            entry.output = _restore(entry, result.reshape(arr.shape))
            return Status.OK()
        _COPY_METRIC.inc()  # send-side tobytes (fallback tier)
        gathered = ctl.gather_data(arr.tobytes())
        if gathered is not None:
            mats = [np.frombuffer(g, dtype=arr.dtype).reshape(arr.shape)
                    for g in gathered]
            # destination d receives block d of every source, in rank order
            per_rank = arr.shape[0] // size
            payloads = []
            for d in range(size):
                block = np.concatenate(
                    [m[d * per_rank:(d + 1) * per_rank] for m in mats])
                _COPY_METRIC.inc()  # per-destination tobytes
                payloads.append(block.tobytes())
            data = ctl.scatter_data(payloads)
        else:
            data = ctl.scatter_data(None)
        result = _np_from_bytes(data, arr.dtype).reshape(arr.shape)
        entry.output = _restore(entry, result)
        return Status.OK()

    # -- reducescatter (TPU-native extension) ----------------------------
    def execute_reducescatter(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        fresh = False
        if response.prescale_factor != 1.0:
            arr = arr * np.asarray(response.prescale_factor, arr.dtype)
            fresh = True
        size = ctl.size
        per_rank = arr.shape[0] // size
        row = int(np.prod(arr.shape[1:], dtype=np.int64)) \
            if arr.ndim > 1 else 1
        per_elems = per_rank * row
        # Routing by UNCOMPRESSED bytes, like allreduce — the wire
        # dtype must not flip the route.
        wire = response.wire_dtype
        ring = self._ring_for(arr.nbytes) \
            if arr.shape[0] % size == 0 else None
        if ring is not None:
            if wire != _wd.WIRE_NONE:
                # Ring legs sum link-by-link, so int8 degrades to bf16
                # (ring_wire) and the reduction happens IN the wire
                # dtype — the compressed-allreduce ring discipline.
                rw = _wd.ring_wire(wire)
                wbuf = compress_send_payload(arr.reshape(-1), rw)
                result = _wd.decompress(
                    ring.reduce_scatter_(wbuf), rw, arr.dtype,
                    per_elems).reshape((per_rank,) + arr.shape[1:])
            else:
                flat = arr.reshape(-1)
                buf = flat if (fresh and flat.flags.writeable) \
                    else flat.copy()
                result = ring.reduce_scatter_(buf).reshape(
                    (per_rank,) + arr.shape[1:])
            if response.postscale_factor != 1.0:
                result = result * np.asarray(response.postscale_factor,
                                             arr.dtype)
            entry.output = _restore(entry, result)
            return Status.OK()
        if wire != _wd.WIRE_NONE:
            result = self._compressed_reducescatter(
                arr, wire, per_elems).reshape(
                (per_rank,) + arr.shape[1:])
            if response.postscale_factor != 1.0:
                result = result * np.asarray(response.postscale_factor,
                                             arr.dtype)
            entry.output = _restore(entry, result)
            return Status.OK()
        if self._zero_copy:
            if ctl.is_coordinator:
                outs = [None] * size
                for r in range(1, size):
                    outs[r] = self._gather_arena.typed(
                        (r - 1) * arr.nbytes, arr.dtype, arr.size)
                ctl.gather_data_into(arr, outs)
                acc = arr.reshape(-1).copy()
                for r in range(1, size):
                    if not _native.sum_into(acc, outs[r]):
                        acc += outs[r]
                acc = acc.reshape(arr.shape)
                ctl.scatter_data_into(
                    [acc[d * per_rank:(d + 1) * per_rank]
                     for d in range(size)], None)
                # acc is fresh: this rank's slice may back the output
                result = acc[:per_rank]
            else:
                ctl.gather_data_into(arr, None)
                flat = np.empty(per_rank * row, arr.dtype)
                ctl.scatter_data_into(None, flat)
                result = flat.reshape((per_rank,) + arr.shape[1:])
            if response.postscale_factor != 1.0:
                result = result * np.asarray(response.postscale_factor,
                                             arr.dtype)
            entry.output = _restore(
                entry, result.reshape((per_rank,) + arr.shape[1:]))
            return Status.OK()

    def _compressed_reducescatter(self, arr: np.ndarray, wire: int,
                                  per_elems: int) -> np.ndarray:
        """Reducescatter star with the negotiated wire dtype on every
        leg, returning this rank's FLAT full-dtype slice (fresh —
        postscale/outputs may alias it). Cast wires accumulate IN the
        wire dtype, exactly like _compressed_allreduce. int8 keeps
        full aggressiveness here — unlike a ring link, the star's
        coordinator can dequantize each rank's payload with ITS OWN
        scale into a full-precision accumulator and requantize each
        OUTPUT slice with a fresh scale, so per-rank scales never
        mix. No error feedback: the output is a world-reduced slice,
        not this rank's next-step gradient, so there is no residual
        chain to compensate."""
        ctl = self._ctl
        size = ctl.size
        src_dtype = arr.dtype
        flat = arr.reshape(-1)
        count = flat.size
        wire_nbytes = _wd.compressed_nbytes(wire, count,
                                            src_dtype.itemsize)
        slice_nbytes = _wd.compressed_nbytes(wire, per_elems,
                                             src_dtype.itemsize)

        if wire == _wd.WIRE_INT8:
            qbuf = compress_send_payload(flat, wire)
            if ctl.is_coordinator:
                if self._zero_copy:
                    outs = [None] * size
                    for r in range(1, size):
                        outs[r] = self._gather_arena.typed(
                            (r - 1) * wire_nbytes, np.uint8,
                            wire_nbytes)
                    ctl.gather_data_into(qbuf, outs)
                    peers = outs[1:]
                else:
                    peers = ctl.gather_data(qbuf)[1:]
                acc = _wd.dequantize(qbuf, src_dtype, count)
                for p in peers:
                    acc += _wd.dequantize(p, src_dtype, count)
                # Every slice — the coordinator's own included — rides
                # through the codec, so all ranks' outputs carry the
                # same quantization treatment.
                payloads = [
                    _wd.quantize(acc[d * per_elems:(d + 1) * per_elems])
                    for d in range(size)]
                if self._zero_copy:
                    ctl.scatter_data_into(payloads, None)
                    rbuf = payloads[0]
                else:
                    rbuf = ctl.scatter_data(payloads)
                return _wd.dequantize(rbuf, src_dtype, per_elems)
            if self._zero_copy:
                ctl.gather_data_into(qbuf, None)
                rbuf = np.empty(slice_nbytes, np.uint8)
                ctl.scatter_data_into(None, rbuf)
            else:
                ctl.gather_data(qbuf)
                rbuf = ctl.scatter_data(None)
            return _wd.dequantize(rbuf, src_dtype, per_elems)

        np_wire = _wd.wire_np_dtype(wire)
        warr = compress_send_payload(
            flat, wire,
            out=self._wire_arena.typed(0, np_wire, count)
            if self._zero_copy else None)
        if ctl.is_coordinator:
            acc = np.array(warr, copy=True)
            if self._zero_copy:
                outs = [None] * size
                for r in range(1, size):
                    outs[r] = self._gather_arena.typed(
                        (r - 1) * wire_nbytes, np_wire, count)
                ctl.gather_data_into(warr, outs)
                peers = outs[1:]
            else:
                peers = ctl.gather_data(warr)[1:]
            _wd.reduce_wire(acc, peers, wire, src_dtype, count)
            slices = [acc[d * per_elems:(d + 1) * per_elems]
                      for d in range(size)]
            if self._zero_copy:
                ctl.scatter_data_into(slices, None)
            else:
                ctl.scatter_data(slices)
            return _wd.decompress(slices[0], wire, src_dtype,
                                  per_elems)
        if self._zero_copy:
            ctl.gather_data_into(warr, None)
            wsl = np.empty(per_elems, np_wire)
            ctl.scatter_data_into(None, wsl)
        else:
            ctl.gather_data(warr)
            wsl = ctl.scatter_data(None)
        return _wd.decompress(wsl, wire, src_dtype, per_elems)
        _COPY_METRIC.inc()  # send-side tobytes (fallback tier)
        gathered = ctl.gather_data(arr.tobytes())
        if gathered is not None:
            _COPY_METRIC.inc()  # writable accumulator materialization
            acc = np.frombuffer(bytearray(gathered[0]), dtype=arr.dtype)
            for data in gathered[1:]:
                src = np.frombuffer(data, dtype=arr.dtype)
                if not _native.sum_into(acc, src):
                    acc += src
            acc = acc.reshape(arr.shape)
            _COPY_METRIC.inc(size)  # per-slice tobytes
            payloads = [acc[d * per_rank:(d + 1) * per_rank].tobytes()
                        for d in range(size)]
            data = ctl.scatter_data(payloads)
        else:
            data = ctl.scatter_data(None)
        result = _np_from_bytes(data, arr.dtype).reshape(
            (per_rank,) + arr.shape[1:])
        if response.postscale_factor != 1.0:
            result = result * np.asarray(response.postscale_factor,
                                         arr.dtype)
        entry.output = _restore(entry, result)
        return Status.OK()

    def execute_barrier(self, entries, response: Response) -> Status:
        gathered = self._ctl.gather_data(b"")
        if gathered is not None:
            self._ctl.broadcast_data(b"")
        else:
            self._ctl.broadcast_data(None)
        return Status.OK()
