"""TCP socket collective backend — the universal host fallback.

Role-equivalent of the reference's MPI CPU ops
(reference: horovod/common/ops/mpi_operations.cc — ``MPIAllreduce``
25-84, ``MPIAllgather`` 95-173, ``MPIBroadcast`` 334-358), which are the
always-enabled last resort in the op priority list. A TPU host has no
MPI; this backend runs the same collectives over the controller's
persistent TCP channels with a star topology (gather → combine at rank 0
→ broadcast/scatter).

Payloads are numpy buffers; jax arrays are staged through host memory
here, exactly like the reference's *CudaOnCPU staging path
(reference: horovod/torch/mpi_ops_v2.cc:78-111). The XLA mesh backend
(xla_ops.py) outranks this one whenever a multi-process JAX world
exists, keeping the data plane on ICI/DCN.

Fused allreduce packs all entries into one contiguous buffer before the
wire round-trip — the fusion-buffer pack/unpack of the reference
(reference: ops/collective_operations.cc:35-63) — so a fused batch costs
one gather+broadcast regardless of tensor count.
"""

from __future__ import annotations

from typing import List

import numpy as np

from horovod_tpu import native as _native
from horovod_tpu.common.controller import Controller
from horovod_tpu.common.message import (
    Response, datatype_to_numpy_dtype, numpy_dtype_to_datatype,
)
from horovod_tpu.common.status import Status
from horovod_tpu.ops.backend import CollectiveBackend


def _to_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    return np.asarray(tensor)


def _np_from_bytes(data: bytes, dtype) -> np.ndarray:
    """Writable array over received bytes. A bare ``np.frombuffer`` over
    ``bytes`` is read-only and would poison outputs (callers expect
    writable tensors, like the reference's allocated outputs)."""
    return np.frombuffer(bytearray(data), dtype=dtype)


def _restore(entry, host_result: np.ndarray):
    """Return the result in the entry's native flavor (jax in → jax out)."""
    if entry.context == "jax":
        import jax
        return jax.device_put(host_result)
    return host_result


class SocketBackend(CollectiveBackend):
    name = "socket"

    def __init__(self, controller: Controller, secret: bytes = b"",
                 config=None):
        self._ctl = controller
        self._secret = secret
        self._ring = None
        self._ring_tried = False
        threshold = 32 * 1024
        if config is not None:
            threshold = getattr(config, "ring_threshold_bytes", threshold)
        self._ring_threshold = threshold

    def enabled(self, entries, response) -> bool:
        return self._ctl.size > 1

    def _ring_for(self, nbytes: int):
        """Ring data plane for large payloads: establish lazily, once,
        at a world-consistent response position (all ranks evaluate the
        same negotiated size against the same threshold). None => star."""
        if self._ring_threshold < 0 or nbytes < self._ring_threshold \
                or self._ctl.size < 3:
            return None
        if not self._ring_tried:
            self._ring_tried = True
            from horovod_tpu.ops import ring as _ring
            self._ring = _ring.establish(self._ctl, self._secret)
        return self._ring

    # -- allreduce -------------------------------------------------------
    def execute_allreduce(self, entries, response: Response) -> Status:
        ctl = self._ctl
        arrays = [_to_numpy(e.tensor) for e in entries]
        dtype = arrays[0].dtype
        # Pack into the fusion buffer (single-tensor case skips the copy,
        # like the reference's MPI_IN_PLACE path, mpi_operations.cc:44-47).
        if len(arrays) == 1:
            fused = np.ascontiguousarray(arrays[0]).reshape(-1)
        else:
            fused = np.concatenate([a.reshape(-1) for a in arrays])
        if response.prescale_factor != 1.0:
            fused = fused * np.asarray(response.prescale_factor, dtype)

        gathered = ctl.gather_data(fused.tobytes())
        if gathered is not None:  # coordinator
            acc = np.frombuffer(bytearray(gathered[0]), dtype=dtype)
            for data in gathered[1:]:
                src = np.frombuffer(data, dtype=dtype)
                if not _native.sum_into(acc, src):
                    acc += src
            result = _np_from_bytes(
                ctl.broadcast_data(acc.tobytes()), dtype)
        else:
            result = _np_from_bytes(ctl.broadcast_data(None), dtype)

        if response.postscale_factor != 1.0:
            result = result * np.asarray(response.postscale_factor, dtype)

        offset = 0
        for e, a in zip(entries, arrays):
            n = a.size
            out = result[offset:offset + n].reshape(a.shape)
            e.output = _restore(e, out)
            offset += n
        return Status.OK()

    # -- allgather -------------------------------------------------------
    def execute_allgather(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries  # allgather responses are not fused (parity)
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        gathered = ctl.gather_data(arr.tobytes())
        if gathered is not None:
            blob = b"".join(gathered)
            result = _np_from_bytes(ctl.broadcast_data(blob), arr.dtype)
        else:
            result = _np_from_bytes(ctl.broadcast_data(None), arr.dtype)
        out_shape = (sum(response.tensor_sizes),) + arr.shape[1:]
        entry.output = _restore(entry, result.reshape(out_shape))
        return Status.OK()

    # -- broadcast -------------------------------------------------------
    def execute_broadcast(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        orig = _to_numpy(entry.tensor)
        # ascontiguousarray promotes 0-d to (1,); keep the true shape —
        # broadcast is the one collective defined on scalars.
        arr = np.ascontiguousarray(orig)
        if ctl.rank == entry.root_rank:
            data = ctl.broadcast_data(arr.tobytes(),
                                      root_rank=entry.root_rank)
        else:
            data = ctl.broadcast_data(None, root_rank=entry.root_rank)
        result = _np_from_bytes(data, arr.dtype).reshape(orig.shape)
        entry.output = _restore(entry, result)
        return Status.OK()

    # -- alltoall (TPU-native extension) ---------------------------------
    def execute_alltoall(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        gathered = ctl.gather_data(arr.tobytes())
        size = ctl.size
        if gathered is not None:
            mats = [np.frombuffer(g, dtype=arr.dtype).reshape(arr.shape)
                    for g in gathered]
            # destination d receives block d of every source, in rank order
            per_rank = arr.shape[0] // size
            payloads = []
            for d in range(size):
                block = np.concatenate(
                    [m[d * per_rank:(d + 1) * per_rank] for m in mats])
                payloads.append(block.tobytes())
            data = ctl.scatter_data(payloads)
        else:
            data = ctl.scatter_data(None)
        result = _np_from_bytes(data, arr.dtype).reshape(arr.shape)
        entry.output = _restore(entry, result)
        return Status.OK()

    # -- reducescatter (TPU-native extension) ----------------------------
    def execute_reducescatter(self, entries, response: Response) -> Status:
        ctl = self._ctl
        (entry,) = entries
        arr = np.ascontiguousarray(_to_numpy(entry.tensor))
        if response.prescale_factor != 1.0:
            arr = arr * np.asarray(response.prescale_factor, arr.dtype)
        gathered = ctl.gather_data(arr.tobytes())
        size = ctl.size
        per_rank = arr.shape[0] // size
        if gathered is not None:
            acc = np.frombuffer(bytearray(gathered[0]), dtype=arr.dtype)
            for data in gathered[1:]:
                src = np.frombuffer(data, dtype=arr.dtype)
                if not _native.sum_into(acc, src):
                    acc += src
            acc = acc.reshape(arr.shape)
            payloads = [acc[d * per_rank:(d + 1) * per_rank].tobytes()
                        for d in range(size)]
            data = ctl.scatter_data(payloads)
        else:
            data = ctl.scatter_data(None)
        result = _np_from_bytes(data, arr.dtype).reshape(
            (per_rank,) + arr.shape[1:])
        if response.postscale_factor != 1.0:
            result = result * np.asarray(response.postscale_factor,
                                         arr.dtype)
        entry.output = _restore(entry, result)
        return Status.OK()

    def execute_barrier(self, entries, response: Response) -> Status:
        gathered = self._ctl.gather_data(b"")
        if gathered is not None:
            self._ctl.broadcast_data(b"")
        else:
            self._ctl.broadcast_data(None)
        return Status.OK()
