"""Bandwidth-optimal ring allreduce over worker↔worker TCP links.

The socket backend's star topology (gather → sum at rank 0 →
broadcast) funnels 2(N-1)·S bytes through the coordinator per op — the
root's NIC and memcpy loop bound the whole world. The reference never
hits this because MPI_Allreduce internally runs ring /
recursive-doubling algorithms (reference: mpi_operations.cc:25-84
delegates to the MPI library). This module supplies the TCP rendering
of that algorithm: the classic 2-phase ring (reduce-scatter then
allgather, Baidu/NCCL style), where every rank sends and receives
exactly 2·S·(N-1)/N bytes over point-to-point links that all run in
parallel — aggregate bandwidth scales with N instead of collapsing
into rank 0.

Topology setup is a one-time rendezvous riding the existing control
plane: each rank opens a data listener, ports are gathered/broadcast
through the coordinator, rank r dials rank (r+1) mod N and accepts
from rank (r-1) mod N. Connections authenticate with the run's HMAC
secret (same Channel framing as the control plane). Whether the ring
is usable is agreed world-wide through ``controller.agree`` — exactly
like the XLA mesh backend's availability vote — so no rank can take
the ring path while another falls back to the star.
"""

from __future__ import annotations

import json
import threading
from typing import List, Optional

import numpy as np

from horovod_tpu import native as _native
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network
from horovod_tpu.common import threadcheck
from horovod_tpu.common.metrics import NOOP_METRIC

_TAG_RING_HELLO = 40
_TAG_RING_DATA = 41


class Ring:
    """Established ring: one channel to the next member, one from the
    previous. Single-threaded use per phase (the background loop).

    ``rank``/``size`` are POSITIONS within the ring's member list —
    for the classic whole-world ring they equal world rank/size; for a
    subset ring (the two-level plane's cross-host ring among local
    roots) ``ranks`` maps position -> world rank so failure blame
    still names the real peer."""

    # Link-bytes counter (metrics plane): the socket backend installs
    # the real counter when it establishes the ring; the class-level
    # no-op keeps unattached/disabled rings free.
    m_link_bytes = NOOP_METRIC

    def __init__(self, rank: int, size: int, next_ch: network.Channel,
                 prev_ch: network.Channel, ranks: List[int] = None):
        self._rank = rank
        self._size = size
        self._next = next_ch
        self._prev = prev_ch
        self._ranks = ranks  # position -> world rank (None = identity)

    def _world_rank(self, pos: int) -> int:
        return self._ranks[pos] if self._ranks is not None else pos

    def _neighbor_error(self, neighbor_pos: int,
                        e: Exception) -> Exception:
        """A dead ring link is a world-level failure whose origin is
        the NEIGHBOR, not this (healthy, detecting) rank — return the
        structured abort so the runtime fans the right origin_rank
        instead of defaulting to the detector."""
        from horovod_tpu.common.status import (
            WorldAbortedError, world_abort_message,
        )
        neighbor = self._world_rank(neighbor_pos)
        cause = (f"ring link to rank {neighbor} failed on "
                 f"rank {self._world_rank(self._rank)}: {e}")
        return WorldAbortedError(world_abort_message(neighbor, cause),
                                 origin_rank=neighbor, cause=cause)

    def _exchange_into(self, send_arr: np.ndarray,
                       recv_arr: np.ndarray) -> None:
        """Full-duplex step: ship ``send_arr`` to the next rank while
        filling ``recv_arr`` from the previous rank. Both are contiguous
        numpy views — nothing is copied through intermediate bytes."""
        self.m_link_bytes.inc(send_arr.nbytes)
        err: List[Exception] = []

        def _send():
            threadcheck.register_role("hvd-ring-send")
            try:
                self._next.send(send_arr, _TAG_RING_DATA)
            except Exception as e:  # surfaced after join
                err.append(e)

        t = threading.Thread(target=_send, name="hvd-ring-send")
        t.start()
        try:
            tag, nbytes = self._prev.recv_into(recv_arr)
        except (ConnectionError, OSError, TimeoutError) as e:
            raise self._neighbor_error((self._rank - 1) % self._size,
                                       e) from e
        finally:
            t.join()
        if err:
            e = err[0]
            if isinstance(e, (ConnectionError, OSError, TimeoutError)):
                raise self._neighbor_error(
                    (self._rank + 1) % self._size, e) from e
            raise e
        if tag != _TAG_RING_DATA:
            raise ConnectionError(f"ring: expected data frame, got {tag}")
        if nbytes != recv_arr.nbytes:
            raise ConnectionError(
                f"ring: expected {recv_arr.nbytes}-byte chunk, "
                f"got {nbytes}")

    def allreduce_(self, buf: np.ndarray) -> np.ndarray:
        """In-place sum-allreduce of a flat contiguous array."""
        n = self._size
        r = self._rank
        cuts = np.linspace(0, buf.size, n + 1).astype(np.int64)
        chunks = [buf[cuts[i]:cuts[i + 1]] for i in range(n)]
        scratch = np.empty(max(c.size for c in chunks), dtype=buf.dtype)
        # Phase 1: reduce-scatter. After step t, chunk (r - t - 1) holds
        # the partial sum of t + 2 ranks; after N-1 steps chunk (r+1)
        # is fully reduced on this rank.
        for step in range(n - 1):
            si = (r - step) % n
            ri = (r - step - 1) % n
            dst = chunks[ri]
            src = scratch[:dst.size]
            self._exchange_into(chunks[si], src)
            if not _native.sum_into(dst, src):
                dst += src
        # Phase 2: allgather of the reduced chunks, received in place.
        for step in range(n - 1):
            si = (r + 1 - step) % n
            ri = (r - step) % n
            self._exchange_into(chunks[si], chunks[ri])
        return buf

    def reduce_scatter_(self, buf: np.ndarray) -> np.ndarray:
        """Phase-1-only ring over ``size`` equal flat chunks; returns a
        view of the fully-reduced chunk this rank owns (chunk index ==
        rank, matching reducescatter's dim-0 partitioning). ``buf.size``
        must divide evenly by the world size."""
        n = self._size
        r = self._rank
        chunk = buf.size // n
        chunks = [buf[i * chunk:(i + 1) * chunk] for i in range(n)]
        scratch = np.empty(chunk, dtype=buf.dtype)
        # Schedule shifted one slot vs allreduce_'s phase 1 so the chunk
        # that ends fully reduced on rank r is chunk r itself.
        for step in range(n - 1):
            si = (r - step - 1) % n
            ri = (r - step - 2) % n
            dst = chunks[ri]
            src = scratch[:dst.size]
            self._exchange_into(chunks[si], src)
            if not _native.sum_into(dst, src):
                dst += src
        return chunks[r]

    def close(self) -> None:
        for ch in (self._next, self._prev):
            try:
                ch.close()
            except Exception:
                pass


def establish(controller, secret: bytes = b"",
              timeout: float = 30.0, hb=None,
              members: List[int] = None) -> Optional[Ring]:
    """One-time ring rendezvous through the control plane. Must be
    called at the same negotiated-response position on every rank.
    Returns None (on every rank, by agreement) if any rank fails.

    ``members`` restricts the ring to a subset of world ranks (the
    two-level plane's cross-host ring among LOCAL ROOTS,
    ops/shm_ops.py) — every rank still runs the control rounds and the
    agree() vote (skipping them would hang the gather), but only
    members open listeners and dial links; non-members get None even
    on success. ``members=None`` is the classic whole-world ring.

    ``hb`` is an optional ``(timeout_s, interval_s)`` liveness deadline
    armed on both ring channels: a neighbor that goes silent mid-
    exchange (host loss — no FIN/RST ever arrives) fails the transfer
    within the bound instead of blocking the background loop forever.
    The deadline resets on every received byte, so a large chunk
    trickling over a slow link never false-positives, and arming costs
    one extra poll(2) per chunk recv — noise against the memcpy+wire
    cost of the data-plane payloads that ride the ring."""
    rank, size = controller.rank, controller.size
    members = list(range(size)) if members is None else list(members)
    is_member = rank in members
    pos = members.index(rank) if is_member else -1
    n = len(members)

    # Phase A — advertise my data port. This control-plane exchange
    # runs UNCONDITIONALLY on every rank (a rank that skipped it would
    # hang the others in gather), advertising port -1 on local failure
    # (port 0 marks a deliberate non-member) so the whole world skips
    # phase B together.
    srv = None
    port = 0
    if is_member:
        try:
            srv = network.listen(0)
            srv.settimeout(timeout)
            port = srv.getsockname()[1]
        except Exception as e:
            hlog.warning(f"ring listen failed on rank {rank}: {e!r}")
            port = -1
    my = json.dumps({"port": port}).encode()
    try:
        gathered = controller.gather_data(my)
        if gathered is not None:  # coordinator
            addrs = []
            for r in range(size):
                p = json.loads(gathered[r].decode())["port"]
                ip = "" if r == 0 else controller.worker_peer_ip(r)
                addrs.append([ip, p])
            blob = controller.broadcast_data(json.dumps(addrs).encode())
        else:
            blob = controller.broadcast_data(None)
        addrs = json.loads(blob.decode())
    except Exception as e:
        hlog.warning(f"ring rendezvous failed on rank {rank}: {e!r}")
        addrs = None

    ring = None
    local_ok = not is_member
    if addrs is not None and is_member \
            and all(addrs[m][1] > 0 for m in members):
        # Phase B — dial next, accept prev. Every listener predates
        # every dial (the rendezvous was the barrier) so connect-then-
        # accept cannot deadlock; accept's timeout bounds the wait if a
        # neighbor's dial failed, and agree() below restores consensus.
        try:
            nxt = members[(pos + 1) % n]
            ip, nport = addrs[nxt]
            if not ip:  # rank 0's data listener sits by the coordinator
                ip = getattr(controller, "coordinator_addr", "127.0.0.1")
            next_ch = network.connect(ip, nport, secret, timeout=timeout,
                                      retry_deadline=timeout)
            next_ch.peer = f"ring rank {nxt} ({next_ch.peer})"
            next_ch.send(json.dumps({"rank": rank}).encode(),
                         _TAG_RING_HELLO)
            sock, _ = srv.accept()
            sock.settimeout(None)
            prev_ch = network.Channel(sock, secret)
            tag, hello = prev_ch.recv()
            if tag != _TAG_RING_HELLO:
                raise ConnectionError("ring handshake failed")
            prev_rank = json.loads(hello.decode())["rank"]
            if prev_rank != members[(pos - 1) % n]:
                raise ConnectionError(
                    f"ring neighbor mismatch: expected "
                    f"{members[(pos - 1) % n]}, got {prev_rank}")
            prev_ch.peer = f"ring rank {prev_rank} ({prev_ch.peer})"
            if hb is not None:
                hb_timeout, hb_interval = hb
                next_ch.arm(hb_timeout, hb_interval)
                prev_ch.arm(hb_timeout, hb_interval)
            ring = Ring(pos, n, next_ch, prev_ch, ranks=members)
            local_ok = True
        except Exception as e:
            hlog.warning(
                f"ring data plane unavailable on rank {rank}: {e!r}")
    if srv is not None:
        try:
            srv.close()
        except Exception:
            pass
    ok = controller.agree(local_ok)
    if not ok:
        if ring is not None:
            ring.close()
        return None
    return ring
