"""Size-1 world backend: collectives degenerate to local transforms.

The reference has no explicit size-1 backend (MPI handles it), but a
TPU-native framework must run single-process without any transport. All
ops preserve the scaling contract (prescale × postscale) so a size-1 run
is numerically identical to a size-N run divided down.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.ops.backend import CollectiveBackend
from horovod_tpu.common.status import Status


def _scale(arr, pre: float, post: float):
    factor = pre * post
    if factor == 1.0:
        return arr
    return arr * np.asarray(factor, dtype=arr.dtype) \
        if isinstance(arr, np.ndarray) else arr * factor


class LocalBackend(CollectiveBackend):
    name = "local"

    def __init__(self, size_fn):
        self._size_fn = size_fn

    def enabled(self, entries, response) -> bool:
        return self._size_fn() == 1

    def execute_allreduce(self, entries, response) -> Status:
        for e in entries:
            e.output = _scale(e.tensor, response.prescale_factor,
                              response.postscale_factor)
        return Status.OK()

    def execute_allgather(self, entries, response) -> Status:
        for e in entries:
            e.output = e.tensor
        return Status.OK()

    def execute_broadcast(self, entries, response) -> Status:
        for e in entries:
            e.output = e.tensor
        return Status.OK()

    def execute_alltoall(self, entries, response) -> Status:
        for e in entries:
            e.output = e.tensor
        return Status.OK()

    def execute_reducescatter(self, entries, response) -> Status:
        for e in entries:
            e.output = _scale(e.tensor, response.prescale_factor,
                              response.postscale_factor)
        return Status.OK()
