"""Framework-neutral collective ops API (numpy / jax host tensors).

Equivalent of the reference's per-framework ``mpi_ops.py`` surfaces
(reference: horovod/torch/mpi_ops.py — sync + async + in-place variants,
handle map, poll/synchronize; horovod/tensorflow/mpi_ops.py), minus the
framework graph integration, which lives in horovod_tpu.jax / .torch.

Every op has a sync and an ``_async`` form returning an integer handle;
``poll`` / ``synchronize`` mirror the reference's handle protocol
(reference: horovod/torch/handle_manager.h:31-42). Auto-generated names
use per-op counters, which agree across ranks as long as ops are created
in the same order — same contract as the reference's
``allreduce.noname.<n>`` naming.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.common import lockdep
from horovod_tpu.common.message import (
    RequestType, numpy_dtype_to_datatype,
)
from horovod_tpu.common.status import (
    HorovodInternalError, Status, WorldAbortedError,
)
from horovod_tpu.common.tensor_table import TensorTableEntry

# Reduction op constants (modern-horovod compatible; the reference's
# `average=True` flag maps onto these).
Average = 0
Sum = 1

_counter_lock = lockdep.lock("ops._counter_lock")
# (scope, kind) -> count. The scope is the active runtime's tenant
# name ('' = default world): each tenant's auto-name sequence must be
# a pure function of ITS OWN submission order — keyed globally, two
# tenants interleaving differently per process would diverge names
# across ranks.
_counters = {}


def _auto_name(kind: str) -> str:
    scope = basics.active_scope()
    with _counter_lock:
        n = _counters.get((scope, kind), 0)
        _counters[(scope, kind)] = n + 1
    return f"{kind}.noname.{n}"


def reset_name_counters(scope=None) -> None:
    """Called by init()/create_tenant so re-initialized worlds agree
    on auto names. ``scope`` clears one world's counters (''=default,
    a tenant name otherwise); None clears everything."""
    with _counter_lock:
        if scope is None:
            _counters.clear()
        else:
            for key in [k for k in _counters if k[0] == scope]:
                del _counters[key]


def _inspect(tensor):
    """-> (payload, context, device, np_dtype, shape, ready_fn)"""
    if isinstance(tensor, np.ndarray) or np.isscalar(tensor):
        arr = np.asarray(tensor)
        return arr, None, -1, arr.dtype, arr.shape, None
    # duck-type jax arrays without importing jax eagerly
    mod = type(tensor).__module__
    if mod.startswith("jax") or hasattr(tensor, "addressable_shards"):
        try:
            dev = sorted(d.id for d in tensor.devices())[0]
        except Exception:
            dev = 0
        # No ready_fn: jax arrays are futures — backends order on the
        # producing computation via their own consumption (np.asarray /
        # device_put), so no ReadyEvent poll is needed (and is_ready()
        # off-thread is pathologically slow on some platforms).
        return (tensor, "jax", dev, np.dtype(tensor.dtype),
                tuple(tensor.shape), None)
    arr = np.asarray(tensor)
    return arr, None, -1, arr.dtype, arr.shape, None


def _enqueue(kind: RequestType, tensor, name: Optional[str],
             root_rank: int = -1, prescale: float = 1.0,
             postscale: float = 1.0) -> int:
    rt = basics.active_runtime()
    payload, ctx, device, np_dtype, shape, ready_fn = _inspect(tensor)
    dtype = numpy_dtype_to_datatype(np_dtype)
    name = name or _auto_name(kind.name.lower())
    handle = rt.handle_manager.allocate()

    entry = TensorTableEntry(tensor_name=name, tensor=payload,
                             root_rank=root_rank, device=device,
                             ready_fn=ready_fn, context=ctx)

    def callback(status: Status) -> None:
        rt.handle_manager.mark_done(handle, status, entry.output)

    entry.callback = callback
    status = rt.enqueue(kind, entry, dtype, shape, prescale, postscale)
    if not status.ok():
        rt.handle_manager.mark_done(handle, status, None)
    return handle


def poll(handle: int) -> bool:
    """True when the op behind ``handle`` has completed
    (reference: horovod/torch/mpi_ops.py poll)."""
    return basics.active_runtime().handle_manager.poll(handle)


def synchronize(handle: int) -> Any:
    """Block until completion; raise on error; return the output tensor
    (reference: horovod/torch/mpi_ops.py synchronize + WaitAndClear).
    A fail-fast world abort surfaces as WorldAbortedError (a
    HorovodInternalError subclass) carrying the originating rank."""
    rt = basics.active_runtime()
    try:
        status = rt.handle_manager.wait(handle)
    except ValueError:
        # Handle ids are unique across world generations, so a stale
        # id is provably from BEFORE an elastic resize (its collective
        # already completed with WorldAbortedError when the old world
        # tore down) — say so. Current-generation misuse (double
        # synchronize, garbage id) keeps the plain ValueError.
        if not rt.handle_manager.from_prior_generation(handle):
            raise
        raise HorovodInternalError(
            f"handle {handle} belongs to a previous world generation: "
            f"async handles do not survive an elastic resize — their "
            f"collectives failed with WorldAbortedError at the abort; "
            f"re-enqueue after recovery") from None
    output = rt.handle_manager.release(handle)
    if not status.ok():
        if status.aborted_by is not None:
            raise WorldAbortedError(status.reason,
                                    origin_rank=status.aborted_by)
        raise HorovodInternalError(status.reason)
    return output


# -- allreduce -----------------------------------------------------------
def _check_scalable_dtype(tensor, op, prescale, postscale, opname):
    """Integer tensors cannot be averaged or scaled — the factor would be
    truncated to 0 in the tensor dtype, silently corrupting results."""
    kind = np.dtype(tensor.dtype).kind if hasattr(tensor, "dtype") \
        else np.asarray(tensor).dtype.kind
    if kind in "iub" and (op == Average or prescale != 1.0
                          or postscale != 1.0):
        raise ValueError(
            f"Averaging or scaling during {opname} is not supported for "
            "integer tensors; use op=Sum with unit scale factors.")


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[int] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    """Sum (or average) ``tensor`` across ranks
    (reference: horovod/torch/mpi_ops.py allreduce_async,
    horovod/tensorflow/__init__.py:46-92)."""
    if average is None and op is None:
        op = Average
    elif op is None:
        op = Average if average else Sum
    _check_scalable_dtype(tensor, op, prescale_factor, postscale_factor,
                          "allreduce")
    if op == Average:
        postscale_factor = postscale_factor / basics.size()
    return _enqueue(RequestType.ALLREDUCE, tensor, name,
                    prescale=prescale_factor, postscale=postscale_factor)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op: Optional[int] = None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> Any:
    return synchronize(allreduce_async(tensor, average, name, op,
                                       prescale_factor, postscale_factor))


def grouped_allreduce_async(tensors, average: Optional[bool] = None,
                            name: Optional[str] = None,
                            op: Optional[int] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0) -> list:
    """Submit a list of tensors as one logical allreduce group under
    derived names ``{name}.<i>`` (later-Horovod ``grouped_allreduce``
    surface; the reference's coordinator batches implicitly via
    fusion — horovod/common/operations.cc:1118-1234). Returns one
    handle per tensor.

    Atomicity is guaranteed, not best-effort: all members enter the
    negotiation in ONE RequestList (Runtime.enqueue_group holds the
    table lock across the whole insert), so a concurrent cycle tick or
    another submitting thread can never split the group — compatible
    members under the fusion threshold land in one fused Response.

    Every member is VALIDATED before any member is enqueued, so a bad
    tensor (unsupported dtype, unscalable integer average) fails the
    whole call without leaking half a group in flight — peers never
    block on members this rank never submitted."""
    if name is None:
        name = _auto_name("grouped_allreduce")
    resolved_op = op if op is not None else (
        Average if (average is None or average) else Sum)
    post = postscale_factor
    if resolved_op == Average:
        post = post / basics.size()

    # Scaling only matters under Average/non-unit factors — hoisting
    # the gate keeps the steady Sum path (DDP-style gradient buckets)
    # from paying a per-tensor dtype probe.
    check_scale = (resolved_op == Average or prescale_factor != 1.0
                   or postscale_factor != 1.0)
    inspected = []
    nbytes_list = []
    for t in tensors:
        # Unsupported payloads AND unsupported dtypes must raise before
        # any enqueue — numpy_dtype_to_datatype is what the enqueue
        # would reject later, so run it here too (e.g. complex64).
        payload, ctx, device, np_dtype, shape, ready_fn = _inspect(t)
        dtype = numpy_dtype_to_datatype(np_dtype)
        if check_scale:
            _check_scalable_dtype(t, resolved_op, prescale_factor,
                                  postscale_factor, "grouped_allreduce")
        inspected.append((payload, ctx, device, dtype, shape, ready_fn))
        numel = 1
        for d in shape:
            numel *= int(d)
        nbytes_list.append(numel * np_dtype.itemsize)

    rt = basics.active_runtime()
    mark_done = rt.handle_manager.mark_done
    handles = rt.handle_manager.allocate_many(len(inspected))
    items = []
    for i, (payload, ctx, device, dtype, shape,
            ready_fn) in enumerate(inspected):
        entry = TensorTableEntry(tensor_name=f"{name}.{i}",
                                 tensor=payload, root_rank=-1,
                                 device=device, ready_fn=ready_fn,
                                 context=ctx)

        def callback(status, entry=entry, handle=handles[i]):
            mark_done(handle, status, entry.output)

        entry.callback = callback
        items.append((entry, dtype, shape))

    # Overlap tier (HOROVOD_OVERLAP_BUCKETS/_BYTES, docs/performance.md
    # Layer 5): split the group into size-balanced CONTIGUOUS buckets,
    # each enqueued as its OWN atomic negotiation batch — early buckets
    # negotiate and reduce while the caller's later gradients are still
    # materializing (jax leaves are futures: the data plane's
    # np.asarray blocks per bucket, so dispatch follows readiness).
    # Tensor names are identical either way, so bucketing never changes
    # numerics — only the fused-batch boundaries.
    bucket_ends = rt.overlap_bucket_plan(nbytes_list)
    if bucket_ends is None:
        # With the overlap runner armed, every grouped call is itself
        # a dispatch unit: record its name set so the background loop
        # peels multi-group pops at group boundaries and each group
        # rides its own in-flight cycle (callers doing their own
        # ready-order bucketing get pipelining without the splitter).
        rt.note_bucket_names(
            entry.tensor_name for entry, _d, _s in items)
        status = rt.enqueue_group(RequestType.ALLREDUCE, items,
                                  prescale_factor, post)
        if not status.ok():
            # Nothing was inserted (all-or-nothing): fail every handle.
            for h in handles:
                rt.handle_manager.mark_done(h, status, None)
        return handles
    rt.note_overlap_buckets(len(bucket_ends))
    start = 0
    for end in bucket_ends:
        rt.note_bucket_names(
            entry.tensor_name for entry, _d, _s in items[start:end])
        status = rt.enqueue_group(RequestType.ALLREDUCE,
                                  items[start:end],
                                  prescale_factor, post)
        if not status.ok():
            # All-or-nothing PER BUCKET: earlier buckets are already
            # in flight (peers expect them); fail this bucket's
            # handles and keep submitting the rest so the world stays
            # in lockstep on every other bucket.
            for h in handles[start:end]:
                rt.handle_manager.mark_done(h, status, None)
        start = end
    return handles


def grouped_allreduce(tensors, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[int] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> list:
    """Blocking grouped allreduce with all-or-nothing error semantics:
    every member handle is drained even when one fails, then the first
    error raises — no member is left silently in flight."""
    handles = grouped_allreduce_async(tensors, average, name, op,
                                      prescale_factor, postscale_factor)
    outs, first_error = [], None
    for h in handles:
        try:
            outs.append(synchronize(h))
        except HorovodInternalError as e:
            outs.append(None)
            if first_error is None:
                first_error = e
    if first_error is not None:
        raise first_error
    return outs


# -- allgather -----------------------------------------------------------
def allgather_async(tensor, name: Optional[str] = None) -> int:
    """Concatenate each rank's tensor along dim 0; dim 0 may differ per
    rank (reference: horovod/common/ops/mpi_operations.cc:95-173
    MPI_Allgatherv semantics)."""
    return _enqueue(RequestType.ALLGATHER, tensor, name)


def allgather(tensor, name: Optional[str] = None) -> Any:
    return synchronize(allgather_async(tensor, name))


def allgather_grad(grad, local_d0: int, name: str) -> np.ndarray:
    """Backward of a named allgather, shared by the framework adapters
    (reference gradient: HorovodAllgather, horovod/torch/mpi_ops.py:
    236-254 and tensorflow/mpi_ops.py:127-148): sum-allreduce the
    upstream gradient of the CONCATENATED output, then keep this
    rank's dim-0 slice, located via an allgather of the per-rank
    sizes (variable dim-0 supported). ``name`` must be the forward's
    resolved op name — the derived grad-op names stay deterministic
    across ranks regardless of backward execution order."""
    sizes = np.asarray(allgather(np.asarray([local_d0], np.int64),
                                 name=f"{name}.grad.sizes"))
    summed = np.asarray(allreduce(np.asarray(grad), op=Sum,
                                  name=f"{name}.grad"))
    off = int(sizes[:basics.rank()].sum())
    return summed[off:off + local_d0]


# -- broadcast -----------------------------------------------------------
def broadcast_async(tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    return _enqueue(RequestType.BROADCAST, tensor, name,
                    root_rank=root_rank)


def broadcast(tensor, root_rank: int, name: Optional[str] = None) -> Any:
    return synchronize(broadcast_async(tensor, root_rank, name))


# -- alltoall (TPU-native extension) -------------------------------------
def alltoall_async(tensor, name: Optional[str] = None) -> int:
    """Scatter dim-0 blocks to every rank and gather their blocks back;
    requires dim 0 divisible by size."""
    return _enqueue(RequestType.ALLTOALL, tensor, name)


def alltoall(tensor, name: Optional[str] = None) -> Any:
    return synchronize(alltoall_async(tensor, name))


# -- reducescatter (TPU-native extension) --------------------------------
def reducescatter_async(tensor, name: Optional[str] = None,
                        op: int = Sum) -> int:
    _check_scalable_dtype(tensor, op, 1.0, 1.0, "reducescatter")
    postscale = 1.0 / basics.size() if op == Average else 1.0
    return _enqueue(RequestType.REDUCESCATTER, tensor, name,
                    postscale=postscale)


def reducescatter(tensor, name: Optional[str] = None, op: int = Sum) -> Any:
    return synchronize(reducescatter_async(tensor, name, op))


# -- barrier -------------------------------------------------------------
def barrier(name: Optional[str] = None) -> None:
    """Block until every rank reaches the barrier."""
    handle = _enqueue(RequestType.BARRIER,
                      np.zeros((), np.uint8), name or _auto_name("barrier"))
    synchronize(handle)
