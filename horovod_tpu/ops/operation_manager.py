"""Priority dispatch across collective backends.

(reference: horovod/common/ops/operation_manager.{h,cc} — ordered op
lists, first ``Enabled()`` op wins, operation_manager.cc:32-60; the
priority order itself is set in ``CreateOperationManager``,
operations.cc:125-158: accelerator ops first, host fallbacks always
last.) Here the order is XLA-mesh (ICI/DCN) → TCP socket (host) →
local (size-1).
"""

from __future__ import annotations

from typing import List

from horovod_tpu.common.message import Response, ResponseType
from horovod_tpu.common.status import Status
from horovod_tpu.common.tensor_table import TensorTableEntry
from horovod_tpu.ops.backend import CollectiveBackend


class OperationManager:
    def __init__(self, backends: List[CollectiveBackend]):
        self._backends = backends

    def attach_finalizer(self, finalizer) -> None:
        """Give every backend the runtime's Finalizer so it may return
        Status.InProgress and complete on a detached thread (reference:
        FinalizeCUDAQueue, cuda_operations.cc:148-179)."""
        for b in self._backends:
            b.finalizer = finalizer

    def attach_timeline(self, timeline) -> None:
        """Give every backend the rank-0 timeline so fusion memcpys show
        up as sub-activities (reference: mpi_operations.cc:35-62)."""
        for b in self._backends:
            b.timeline = timeline

    def close(self) -> None:
        """Release backend resources (ring channels, shm mappings) at
        shutdown."""
        for b in self._backends:
            close = getattr(b, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def _pick(self, entries, response) -> CollectiveBackend:
        for b in self._backends:
            if b.enabled(entries, response):
                return b
        raise RuntimeError(
            f"No collective backend enabled for response "
            f"{response.response_type.name} ({response.tensor_names})")

    def pick(self, entries: List[TensorTableEntry],
             response: Response) -> CollectiveBackend:
        """The backend that WOULD execute this batch — the runtime's
        speculative fused cycle probes it (fused_cycle_reducible)
        before deciding to piggyback the payload on the negotiation
        round instead of dispatching here."""
        return self._pick(entries, response)

    def execute(self, entries: List[TensorTableEntry],
                response: Response) -> Status:
        backend = self._pick(entries, response)
        rt = response.response_type
        if rt == ResponseType.ALLREDUCE:
            return backend.execute_allreduce(entries, response)
        if rt == ResponseType.ALLGATHER:
            return backend.execute_allgather(entries, response)
        if rt == ResponseType.BROADCAST:
            return backend.execute_broadcast(entries, response)
        if rt == ResponseType.ALLTOALL:
            return backend.execute_alltoall(entries, response)
        if rt == ResponseType.REDUCESCATTER:
            return backend.execute_reducescatter(entries, response)
        if rt == ResponseType.BARRIER:
            return backend.execute_barrier(entries, response)
        raise ValueError(f"Cannot execute response type {rt}")
