"""Priority dispatch across collective backends.

(reference: horovod/common/ops/operation_manager.{h,cc} — ordered op
lists, first ``Enabled()`` op wins, operation_manager.cc:32-60; the
priority order itself is set in ``CreateOperationManager``,
operations.cc:125-158: accelerator ops first, host fallbacks always
last.) Here the order is XLA-mesh (ICI/DCN) → TCP socket (host) →
local (size-1).
"""

from __future__ import annotations

import time
from typing import List

from horovod_tpu.common.message import Response, ResponseType
from horovod_tpu.common.status import Status
from horovod_tpu.common.tensor_table import TensorTableEntry
from horovod_tpu.ops.backend import CollectiveBackend


class OperationManager:
    def __init__(self, backends: List[CollectiveBackend]):
        self._backends = backends
        self._metrics_on = False
        self._fusion_threshold_fn = None

    def attach_metrics(self, registry, fusion_threshold_fn=None) -> None:
        """Install the per-op-type instrumentation the runtime's
        registry provides (the disabled registry hands back no-op
        metrics, keeping every call free): op counts, payload bytes
        per collective kind, collective wall-time histograms (issue
        time for async backends — completion rides the finalizer), and
        the fusion-buffer fill ratio against the world threshold.
        Backends get their own per-plane counters via
        CollectiveBackend.attach_metrics."""
        from horovod_tpu.common.metrics import RATIO_BUCKETS
        self._metrics_on = bool(registry.enabled)
        self._fusion_threshold_fn = fusion_threshold_fn
        bytes_names = {
            ResponseType.ALLREDUCE: "hvd_bytes_allreduced_total",
            ResponseType.ALLGATHER: "hvd_bytes_allgathered_total",
            ResponseType.BROADCAST: "hvd_bytes_broadcast_total",
            ResponseType.ALLTOALL: "hvd_bytes_alltoall_total",
            ResponseType.REDUCESCATTER:
                "hvd_bytes_reducescattered_total",
        }
        self._m_ops = {}
        self._m_bytes = {}
        self._m_wall = {}
        for rt, bname in bytes_names.items():
            op = rt.name.lower()
            self._m_ops[rt] = registry.counter(
                f'hvd_ops_total{{op="{op}"}}')
            self._m_bytes[rt] = registry.counter(bname)
            self._m_wall[rt] = registry.histogram(
                f'hvd_collective_seconds{{op="{op}"}}',
                "collective execution wall time (issue time for "
                "async backends)")
        self._m_ops[ResponseType.BARRIER] = registry.counter(
            'hvd_ops_total{op="barrier"}')
        self._m_fill = registry.histogram(
            "hvd_fusion_fill_ratio",
            "fused batch bytes / fusion threshold", RATIO_BUCKETS)
        for b in self._backends:
            b.attach_metrics(registry)

    def attach_finalizer(self, finalizer) -> None:
        """Give every backend the runtime's Finalizer so it may return
        Status.InProgress and complete on a detached thread (reference:
        FinalizeCUDAQueue, cuda_operations.cc:148-179)."""
        for b in self._backends:
            b.finalizer = finalizer

    def attach_timeline(self, timeline) -> None:
        """Give every backend the rank-0 timeline so fusion memcpys show
        up as sub-activities (reference: mpi_operations.cc:35-62)."""
        for b in self._backends:
            b.timeline = timeline

    def note_cache_epoch(self, epoch: int) -> None:
        """Fan a ResponseCache epoch bump out to every backend that
        holds epoch-coupled compiled state (the XLA mesh backend's
        executable cache); called by the runtime at the broadcast-
        driven position where the epoch moves, so evictions happen at
        the same stream point on every rank."""
        for b in self._backends:
            note = getattr(b, "note_cache_epoch", None)
            if note is not None:
                note(epoch)

    def close(self) -> None:
        """Release backend resources (ring channels, shm mappings) at
        shutdown."""
        for b in self._backends:
            close = getattr(b, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def _pick(self, entries, response) -> CollectiveBackend:
        for b in self._backends:
            if b.enabled(entries, response):
                return b
        raise RuntimeError(
            f"No collective backend enabled for response "
            f"{response.response_type.name} ({response.tensor_names})")

    def pick(self, entries: List[TensorTableEntry],
             response: Response) -> CollectiveBackend:
        """The backend that WOULD execute this batch — the runtime's
        speculative fused cycle probes it (fused_cycle_reducible)
        before deciding to piggyback the payload on the negotiation
        round instead of dispatching here."""
        return self._pick(entries, response)

    def execute(self, entries: List[TensorTableEntry],
                response: Response) -> Status:
        backend = self._pick(entries, response)
        rt = response.response_type
        if not self._metrics_on:
            return self._dispatch(backend, rt, entries, response)
        nbytes = sum(getattr(e.tensor, "nbytes", 0) for e in entries)
        op_counter = self._m_ops.get(rt)
        if op_counter is not None:
            op_counter.inc()
        byte_counter = self._m_bytes.get(rt)
        if byte_counter is not None:
            byte_counter.inc(nbytes)
        backend.m_ops.inc()
        backend.m_bytes.inc(nbytes)
        if len(entries) > 1 and self._fusion_threshold_fn is not None:
            threshold = self._fusion_threshold_fn()
            if threshold > 0:
                self._m_fill.observe(nbytes / threshold)
        t0 = time.perf_counter()
        try:
            return self._dispatch(backend, rt, entries, response)
        finally:
            wall = self._m_wall.get(rt)
            if wall is not None:
                wall.observe(time.perf_counter() - t0)

    @staticmethod
    def _dispatch(backend, rt, entries, response) -> Status:
        if rt == ResponseType.ALLREDUCE:
            return backend.execute_allreduce(entries, response)
        if rt == ResponseType.ALLGATHER:
            return backend.execute_allgather(entries, response)
        if rt == ResponseType.BROADCAST:
            return backend.execute_broadcast(entries, response)
        if rt == ResponseType.ALLTOALL:
            return backend.execute_alltoall(entries, response)
        if rt == ResponseType.REDUCESCATTER:
            return backend.execute_reducescatter(entries, response)
        if rt == ResponseType.BARRIER:
            return backend.execute_barrier(entries, response)
        raise ValueError(f"Cannot execute response type {rt}")
