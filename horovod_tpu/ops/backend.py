"""Collective backend interface.

Equivalent of the reference's ``HorovodOp`` hierarchy and its
``Enabled()`` protocol (reference: horovod/common/ops/
collective_operations.h:29-117): a backend reports whether it can run a
given batch of entries, and the OperationManager walks a priority list,
first enabled wins (reference: ops/operation_manager.cc:32-60).

Backends execute a whole (possibly fused) Response at once — the fusion
buffer pack/collective/unpack of the reference's
``MemcpyInFusionBuffer``/``MemcpyOutFusionBuffer``
(reference: ops/collective_operations.cc:35-63) happens inside
``execute_allreduce`` so each backend can fuse the way its transport
likes (numpy concatenation for the socket path, XLA concat/slice —
fused into the collective by the compiler — for the mesh path).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import List

from horovod_tpu.common.message import Response
from horovod_tpu.common.metrics import NOOP_METRIC
from horovod_tpu.common.status import Status
from horovod_tpu.common.tensor_table import TensorTableEntry
from horovod_tpu.common.timeline import NOOP_TIMELINE


class CollectiveBackend:
    name = "abstract"

    # Set by OperationManager.attach_finalizer when async completion is
    # enabled; backends that issue asynchronously submit a completion
    # closure and return Status.InProgress.
    finalizer = None

    # Set by OperationManager.attach_timeline (rank 0 with
    # HOROVOD_TIMELINE only); host planes wrap their fusion pack/unpack
    # in MEMCPY_IN/OUT_FUSION_BUFFER activities so timelines show where
    # fusion time goes (reference: mpi_operations.cc:35-62).
    timeline = NOOP_TIMELINE

    # Per-plane observability (common/metrics.py), installed by
    # OperationManager.attach_metrics; the class-attribute no-ops keep
    # unattached/disabled paths free. Subclasses may override
    # attach_metrics (calling super) to add plane-specific metrics.
    m_ops = NOOP_METRIC
    m_bytes = NOOP_METRIC

    def attach_metrics(self, registry) -> None:
        self.m_ops = registry.counter(
            f'hvd_backend_ops_total{{backend="{self.name}"}}',
            "collective batches executed by this data plane")
        self.m_bytes = registry.counter(
            f'hvd_backend_bytes_total{{backend="{self.name}"}}',
            "payload bytes moved through this data plane")

    @contextmanager
    def activity(self, names, act, enabled: bool = True):
        """Timeline sub-activity span; the finally guarantees the span
        closes even when the wrapped transport/pack raises, so an error
        mid-batch cannot misnest every later event in the trace."""
        if not enabled:
            yield
            return
        self.timeline.activity_start_all(names, act)
        try:
            yield
        finally:
            self.timeline.activity_end_all(names)

    def enabled(self, entries: List[TensorTableEntry],
                response: Response) -> bool:
        raise NotImplementedError

    def fused_cycle_reducible(self, nbytes: int) -> bool:
        """True when a fused allreduce of ``nbytes`` would ride a
        star through the coordinator's control channels anyway — the
        precondition for the speculative fused cycle (runtime.py) to
        piggyback the payload on the negotiation round. Planes with
        their own transport (shm, ring, XLA mesh) say False so
        speculation never steals a batch from a faster data plane."""
        return False

    def execute_allreduce(self, entries, response) -> Status:
        raise NotImplementedError

    def execute_allgather(self, entries, response) -> Status:
        raise NotImplementedError

    def execute_broadcast(self, entries, response) -> Status:
        raise NotImplementedError

    def execute_alltoall(self, entries, response) -> Status:
        raise NotImplementedError

    def execute_reducescatter(self, entries, response) -> Status:
        raise NotImplementedError

    def execute_barrier(self, entries, response) -> Status:
        return Status.OK()
