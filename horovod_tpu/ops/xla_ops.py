"""XLA mesh collective backend — the TPU data plane.

Role-equivalent of the reference's NCCL ops
(reference: horovod/common/ops/nccl_operations.cc — ``NCCLAllreduce``
60-109, ``NCCLHierarchicalAllreduce`` 167-372), re-founded on XLA: the
negotiated (fused) Response is executed as a jit-compiled collective
over a ``jax.sharding.Mesh`` with one representative device per
process, so the bytes ride ICI/DCN and never touch the host NIC.

Why this is correct in multi-controller JAX: every process must issue
identical XLA computations in identical order. The coordinator's
broadcast ResponseList establishes exactly that total order (see
common/coordinator.py), so each process independently arriving here will
request the same compiled executable with the same shapes.

Compiled executables are cached per (op, shape-signature, dtype) — the
TPU-native realization of the reference's fusion-buffer reuse
(reference: common/fusion_buffer_manager.cc:21-45): instead of one
persistent scratch buffer, we keep one persistent *program* per bucket
signature, and XLA reuses its own buffers across calls.

Enabled only when a multi-process JAX world exists
(``jax.process_count() > 1``); single-process worlds take the in-jit
SPMD path (horovod_tpu/spmd) or the local backend instead.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from horovod_tpu.common import lockdep
from horovod_tpu.common import logging as hlog
from horovod_tpu.common.invariants import world_coherent
from horovod_tpu.common.message import Response
from horovod_tpu.common.status import Status
from horovod_tpu.ops.backend import CollectiveBackend

_AXIS = "hvd_proc"
_ICI_AXIS = "ici"


def _shard_map(body, mesh, in_specs, out_specs):
    """Version-portable shard_map with the replication checker off
    (collectives guarantee their own output sharding; the static
    checker cannot see that). The version gate lives in the sanctioned
    compat shim."""
    from horovod_tpu.compat import jaxshim
    return jaxshim.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)


def ragged_psum_wins(sizes, slice_numels, world_size: int) -> bool:
    """Skew guard for the fused variable-dim0 allgather: True when the
    masked-psum rendering moves fewer bytes than the padded all_gather.

    The padded all_gather's wire traffic scales with
    ``world_size x max(dim0)`` per entry, the reference's
    ``MPI_Allgatherv`` with the TRUE bytes
    (reference: mpi_operations.cc:95-173). A psum over a zero-scattered
    output buffer moves ~2x the true bytes (reduce-scatter +
    all-gather phases), so it wins once the skew exceeds roughly
    ``max(dim0) > 2 x mean(dim0)``. Inputs come from the broadcast
    Response (entry-major ``sizes``), so every rank decides alike.
    """
    if world_size <= 1:
        return False
    padded_elems = 0
    psum_elems = 0
    for ec, sn in enumerate(slice_numels):
        rows = sizes[ec * world_size:(ec + 1) * world_size]
        m = max(rows)
        padded_elems += world_size * m * sn
        # psum buffer: true rows + one max-block of overlap slack
        psum_elems += (sum(rows) + m) * sn
    if psum_elems > np.iinfo(np.int32).max:
        # The psum rendering scatters blocks at element offsets that
        # must index its assembled buffer; past int32 range a
        # 32-bit offset (jax canonicalizes int64 down without
        # jax_enable_x64) would silently wrap and corrupt the
        # output — the padded all_gather has no such offsets, so it
        # carries oversized buffers regardless of skew.
        return False
    return 2 * psum_elems < padded_elems


class XlaMeshBackend(CollectiveBackend):
    name = "xla_mesh"

    def __init__(self, controller, config=None):
        self._ctl = controller
        self._config = config
        self._lock = lockdep.lock("xla_ops.XlaMeshBackend._lock")
        self._mesh = None
        self._mesh2d = None   # (cross, local) factored mesh, see below
        self._my_device = None
        self._cache: Dict[Tuple, object] = {}
        self._cache_epoch = -1
        self._available = None
        self._m_compiles = None  # set by attach_metrics
        self._m_cache_size = None

    def attach_metrics(self, registry) -> None:
        super().attach_metrics(registry)
        # Compilation is the mesh plane's dominant first-use cost; a
        # climbing compile count in steady state means shape churn is
        # defeating the executable cache.
        self._m_compiles = registry.counter(
            "hvd_xla_compiles_total",
            "collective executables built (shard_map jit)")
        self._m_cache_size = registry.gauge(
            "hvd_xla_compiled_cache_size",
            "distinct compiled collective executables held")

    def _rank_fn(self):
        return self._ctl.rank

    def _size_fn(self):
        return self._ctl.size

    def _probe_local(self) -> bool:
        """This rank's view of mesh availability (may be wrong on other
        ranks — never act on it alone)."""
        try:
            import jax
            if jax.process_count() <= 1:
                return False
            if jax.process_count() != self._size_fn():
                hlog.warning(
                    f"JAX world has {jax.process_count()} processes but "
                    f"horovod world has {self._size_fn()}; disabling the "
                    "XLA mesh backend.")
                return False
            if jax.process_index() != self._rank_fn():
                # Mesh slot r is interpreted as horovod rank r (broadcast
                # roots, allgather slots, alltoall blocks); if the
                # launcher numbered ranks differently from JAX process
                # indices, results would be silently permuted.
                hlog.warning(
                    f"horovod rank {self._rank_fn()} != jax process index "
                    f"{jax.process_index()}; disabling the XLA mesh "
                    "backend (collectives fall back to the socket path).")
                return False
            from horovod_tpu.compat import jaxshim
            # One representative device per process, ordered by the
            # horovod rank == jax process index contract established by
            # the launcher (run/launch.py exports both).
            by_proc: Dict[int, list] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, []).append(d)
            reps = [sorted(by_proc[p], key=lambda d: d.id)[0]
                    for p in sorted(by_proc)]
            self._mesh = jaxshim.make_raw_mesh(np.array(reps), (_AXIS,))
            self._my_device = reps[jax.process_index()]
            self._maybe_build_hierarchical_mesh(reps)
            return True
        except Exception as e:  # jax missing / not distributed
            hlog.debug(f"XLA mesh backend unavailable: {e}")
            return False

    def _maybe_build_hierarchical_mesh(self, reps) -> None:
        """HOROVOD_HIERARCHICAL_ALLREDUCE / _ALLGATHER: factor the flat
        proc mesh into (cross, local) axes so collectives decompose
        into an intra-host stage riding ICI and a cross-host stage
        riding DCN — the XLA rendering of NCCLHierarchicalAllreduce's
        reduce-scatter → cross allreduce → allgather (reference:
        nccl_operations.cc:167-372) and MPIHierarchicalAllgather's
        node-shared buffer + cross allgatherv (reference:
        mpi_operations.cc:179-329). Allreduce is order-free; the
        hierarchical allgather reshapes (cross, local) back into rank
        order, which the contiguous per-host rank layout guarantees.
        Other rank-ordered ops (alltoall, broadcast roots) stay on the
        flat mesh where slot r is unambiguously rank r."""
        from horovod_tpu.compat import jaxshim
        cfg = self._config
        topo = self._ctl.topology
        if cfg is None or topo is None or not (
                getattr(cfg, "hierarchical_allreduce", False)
                or getattr(cfg, "hierarchical_allgather", False)):
            return
        if not topo.is_homogeneous or topo.local_size <= 1:
            return
        # Requires the launcher's contiguous per-host rank layout
        # (rank == cross_rank * local_size + local_rank).
        if topo.rank != topo.cross_rank * topo.local_size + \
                topo.local_rank:
            hlog.warning("hierarchical collectives disabled (allreduce/"
                         "allgather): ranks are not grouped "
                         "contiguously per host")
            return
        grid = np.array(reps).reshape(topo.cross_size, topo.local_size)
        self._mesh2d = jaxshim.make_raw_mesh(grid, ("cross", "local"))

    def _ensure_mesh(self) -> bool:
        if self._available is not None:
            return self._available
        # The decision must be world-consistent: if any rank can't join
        # the mesh (jax init failed, rank permutation, device mismatch),
        # EVERY rank must take the socket path or the job deadlocks with
        # some ranks inside a psum and others in a TCP gather. All ranks
        # reach this point at the same position of the negotiated
        # response stream, so the agreement round is ordered identically
        # everywhere.
        local_ok = self._probe_local()
        self._available = self._ctl.agree(local_ok)
        if local_ok and not self._available:
            hlog.warning("XLA mesh backend disabled: another rank "
                         "cannot join the device mesh; all collectives "
                         "take the socket path.")
        return self._available

    def enabled(self, entries, response) -> bool:
        if self._size_fn() <= 1:
            return False
        # Only device tensors (jax arrays) take the mesh path; host numpy
        # tensors fall through to the socket backend, mirroring the
        # reference's CPU-tensors-use-MPI split
        # (reference: operations.cc:125-158 op registration order).
        if any(e.context != "jax" for e in entries):
            return False
        return self._ensure_mesh()

    # ------------------------------------------------------------------
    def _global_input(self, flat, mesh=None, axes=_AXIS):
        """Wrap this process's flat buffer as one shard of a global array
        over the proc axis (or the factored (cross, local) axes)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.compat import jaxshim
        size = self._size_fn()
        local = jax.device_put(flat, self._my_device)
        return jax.make_array_from_single_device_arrays(
            (size * flat.shape[0],) + flat.shape[1:],
            jaxshim.named_sharding(mesh or self._mesh, P(axes)), [local])

    def _compiled(self, key, builder):
        with self._lock:
            fn = self._cache.get(key)
            if fn is None:
                fn = builder()
                self._cache[key] = fn
                if self._m_compiles is not None:
                    self._m_compiles.inc()
                    self._m_cache_size.set(len(self._cache))
        return fn

    def note_cache_epoch(self, epoch: int) -> None:
        """ResponseCache epoch bump: every compiled executable was
        built for verdicts of the previous epoch's responses — evict
        them like every other world-replicated plan (the runtime calls
        this at the same broadcast-driven position on all ranks)."""
        with self._lock:
            if epoch != self._cache_epoch:
                self._cache_epoch = epoch
                self._cache.clear()
                if self._m_cache_size is not None:
                    self._m_cache_size.set(0)

    @staticmethod
    def _verdict_sig(response):
        """The negotiated attributes a compiled program bakes in beyond
        its shapes: the coordinator-stamped wire dtype and algorithm.
        Without them an autotune verdict flip (e.g. ALG_DEFAULT ->
        ALG_TWOLEVEL, or a wire-dtype move) would replay the stale
        program keyed only on (op, shape, dtype)."""
        if response is None:
            return ()
        return (response.wire_dtype, response.algorithm)

    def _run_shard_op(self, kind: str, flat, out_specs, body, extra=(),
                      mesh=None, axes=_AXIS, response=None):
        """jit(shard_map(body)) over the proc mesh, one shard per rank."""
        import jax
        from jax.sharding import PartitionSpec as P
        mesh = mesh or self._mesh
        key = (kind, flat.shape, str(flat.dtype), extra, axes,
               self._verdict_sig(response))

        def build():
            # Replication checker off (_shard_map): it can't statically
            # infer all_gather/psum results are replicated; semantics
            # are guaranteed by the collective itself.
            m = _shard_map(body, mesh=mesh,
                           in_specs=P(axes), out_specs=out_specs)
            return jax.jit(m)

        fn = self._compiled(key, build)
        garr = self._global_input(flat, mesh=mesh, axes=axes)
        out = fn(garr)
        return out

    @staticmethod
    def _observe(outs) -> Status:
        """Block until the issued collective's outputs are really done.
        block_until_ready alone is not enough on the axon platform
        (it can return before execution finishes), so also fetch one
        element of each output — a value fetch is a true sync point."""
        try:
            import jax
            jax.block_until_ready(outs)
            for o in outs:
                if hasattr(o, "ndim") and getattr(o, "size", 0):
                    np.asarray(jax.device_get(o[(0,) * o.ndim]))
            return Status.OK()
        except Exception as ex:
            return Status.UnknownError(
                f"async collective completion failed: {ex!r}")

    def _complete(self, entries) -> Status:
        """Async completion (reference: FinalizeCUDAQueue,
        cuda_operations.cc:148-179): the jitted collective is already
        in flight; hand the output arrays to a finalizer thread that
        observes readiness and fires the callbacks, and return
        InProgress so the negotiation loop keeps cycling."""
        fin = self.finalizer
        if fin is None:
            return Status.OK()
        outs = [e.output for e in entries]

        def finalize():
            st = self._observe(outs)
            for e in entries:
                if e.callback:
                    try:
                        e.callback(st)
                    except Exception as ex:
                        # One adapter callback must not starve the rest
                        # of the batch of their completions.
                        hlog.error(f"completion callback for "
                                   f"{e.tensor_name} raised: {ex!r}")

        if not fin.submit(finalize):
            # Draining: observe readiness inline; the loop fires the
            # callbacks synchronously on a non-InProgress status.
            return self._observe(outs)
        return Status.InProgress()

    # -- allreduce -------------------------------------------------------
    def execute_allreduce(self, entries, response: Response) -> Status:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        arrays = [e.tensor for e in entries]
        sizes = [int(np.prod(np.asarray(a.shape))) if a.ndim else 1
                 for a in arrays]
        flat = (jnp.concatenate([jnp.ravel(a) for a in arrays])
                if len(arrays) > 1 else jnp.ravel(arrays[0]))
        pre, post = response.prescale_factor, response.postscale_factor
        # Factored (cross, local) psum when hierarchical allreduce is
        # on: XLA emits the intra-host stage on ICI and the cross-host
        # stage on DCN.
        if self._mesh2d is not None and getattr(
                self._config, "hierarchical_allreduce", False):
            mesh, axes = self._mesh2d, ("cross", "local")
        else:
            mesh, axes = self._mesh, _AXIS

        def body(x):
            if pre != 1.0:
                x = x * jnp.asarray(pre, x.dtype)
            y = jax.lax.psum(x, axes)
            if post != 1.0:
                y = y * jnp.asarray(post, y.dtype)
            return y

        out = self._run_shard_op("allreduce", flat, P(), body,
                                 extra=(pre, post), mesh=mesh, axes=axes,
                                 response=response)
        fused = out.addressable_data(0)
        offset = 0
        for e, a, n in zip(entries, arrays, sizes):
            e.output = jax.device_put(
                fused[offset:offset + n].reshape(a.shape))
            offset += n
        return self._complete(entries)

    # -- allgather (variable dim0 via pad + slice; fused multi-entry) ----
    def execute_allgather(self, entries, response: Response) -> Status:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        size = self._size_fn()
        sizes = response.tensor_sizes  # entry-major: [ec*size + rc]
        hier = (self._mesh2d is not None and getattr(
            self._config, "hierarchical_allgather", False))
        # Ragged-skew guard: under heavy dim-0 skew the padded
        # all_gather's N x max wire bytes dwarf the true payload; the
        # masked-psum rendering moves ~2x the TRUE bytes instead. The
        # decision is a pure function of the broadcast response, so
        # every rank picks the same rendering. Flat mesh only: under
        # hierarchical allgather the displaced cost is the two-level
        # gather's, which the byte model doesn't describe, and the
        # psum would cross DCN undecomposed.
        slice_numels = []
        for ec, e in enumerate(entries):
            sn = 1
            for d in e.tensor.shape[1:]:
                sn *= int(d)
            slice_numels.append(sn)
        if not hier and ragged_psum_wins(sizes, slice_numels, size):
            return self._execute_allgather_psum(entries, response,
                                                slice_numels)
        # Pad every entry to its own max dim-0, flatten, concatenate:
        # one all_gather moves the whole fused batch — the TPU
        # rendering of the reference's fused MPI_Allgatherv
        # (reference: mpi_operations.cc:95-173).
        max_dim0s, slices, flats = [], [], []
        for ec, e in enumerate(entries):
            x = e.tensor
            rows = sizes[ec * size:(ec + 1) * size]
            m = max(rows)
            pad = m - x.shape[0]
            if pad:
                x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            max_dim0s.append(m)
            slices.append(tuple(x.shape[1:]))
            flats.append(jnp.ravel(x))
        flat = (jnp.concatenate(flats) if len(flats) > 1 else flats[0])

        if hier:
            # Two-level gather (reference: MPIHierarchicalAllgather,
            # mpi_operations.cc:179-329): gather the host's shards
            # locally (ICI), then exchange whole host blocks across
            # hosts (DCN). The (cross, local) result reshapes exactly
            # into rank order under the contiguous per-host layout.
            local_size = self._mesh2d.shape["local"]
            cross_size = self._mesh2d.shape["cross"]

            def body(t):
                g_local = jax.lax.all_gather(t, "local")
                g = jax.lax.all_gather(g_local, "cross")
                return g.reshape((cross_size * local_size,) + t.shape)

            out = self._run_shard_op(
                "allgather_hier", flat, P(), body,
                extra=(tuple(sizes),), mesh=self._mesh2d,
                axes=("cross", "local"), response=response)
        else:
            def body(t):
                return jax.lax.all_gather(t, _AXIS)

            out = self._run_shard_op("allgather", flat, P(), body,
                                     extra=(tuple(sizes),),
                                     response=response)
        # out: [size, sum(max_dim0_e*slice_e)] replicated; for each
        # entry slice each rank's real rows out of its padded block.
        g = out.addressable_data(0)
        ent_off = 0
        for ec, e in enumerate(entries):
            rows = sizes[ec * size:(ec + 1) * size]
            slice_shape = slices[ec]
            slice_numel = slice_numels[ec]
            block = max_dim0s[ec] * slice_numel
            parts = [
                g[r][ent_off:ent_off + rows[r] * slice_numel].reshape(
                    (rows[r],) + slice_shape)
                for r in range(size)]
            e.output = jax.device_put(
                jnp.concatenate(parts, axis=0) if size > 1
                else parts[0])
            ent_off += block
        return self._complete(entries)

    def _execute_allgather_psum(self, entries, response: Response,
                                slice_numels) -> Status:
        """Skewed (allgatherv-shaped) fused allgather: each rank
        zero-scatters its padded block at its TRUE row offset into a
        buffer laid out by real row counts, and one psum assembles the
        result — wire bytes track ~2x the true payload instead of the
        padded path's N x max (the guard in execute_allgather picks
        this rendering only when that is the cheaper side; reference
        behavior target: MPI_Allgatherv, mpi_operations.cc:95-173).

        Correctness of the overlap: rank r's padded block spans
        ``[off_r, off_r + max*sn)`` while rank r+1's rows begin at
        ``off_r + rows_r*sn`` — every position a rank does not own
        receives only its padding ZEROS, so the psum reconstructs each
        row exactly once. One trailing max-block of slack per entry
        keeps the last rank's padded block in bounds."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        size = self._size_fn()
        sizes = response.tensor_sizes
        max_dim0s, slice_shapes, flats = [], [], []
        rank_offsets = []   # [entry][rank] element offset of true rows
        total = 0
        for ec, e in enumerate(entries):
            x = e.tensor
            rows = sizes[ec * size:(ec + 1) * size]
            m = max(rows)
            sn = slice_numels[ec]
            pad = m - x.shape[0]
            if pad:
                x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            max_dim0s.append(m)
            slice_shapes.append(tuple(x.shape[1:]))
            flats.append(jnp.ravel(x))
            offs, acc = [], 0
            for r in range(size):
                offs.append(total + acc * sn)
                acc += rows[r]
            rank_offsets.append(offs)
            total += (acc + m) * sn   # true rows + overlap slack
        flat = (jnp.concatenate(flats) if len(flats) > 1 else flats[0])
        # int64: ragged_psum_wins guarantees the total fits int32, but
        # the OFFSET arithmetic above (cumulative products) must never
        # wrap while computing it; with jax_enable_x64 the wide dtype
        # survives into the scatter as well.
        offs_const = np.asarray(rank_offsets, np.int64)  # [E, size]
        block_lens = [m * sn for m, sn in zip(max_dim0s, slice_numels)]

        def body(x):
            r = jax.lax.axis_index(_AXIS)
            buf = jnp.zeros((total,), x.dtype)
            in_off = 0
            for ec, blen in enumerate(block_lens):
                blk = jax.lax.dynamic_slice(x, (in_off,), (blen,))
                off = jnp.take(jnp.asarray(offs_const[ec]), r)
                buf = jax.lax.dynamic_update_slice(buf, blk, (off,))
                in_off += blen
            # psum promotes bool to int; each slot has exactly one
            # non-zero contributor, so casting back is exact.
            return jax.lax.psum(buf, _AXIS).astype(x.dtype)

        # slice_numels joins the key: the body's offsets/layout derive
        # from them, and same flat shape + sizes with different slice
        # widths would otherwise collide on a wrong executable.
        out = self._run_shard_op("allgather_psum", flat, P(), body,
                                 extra=(tuple(sizes),
                                        tuple(slice_numels)),
                                 response=response)
        g = out.addressable_data(0)
        for ec, e in enumerate(entries):
            rows = sizes[ec * size:(ec + 1) * size]
            sn = slice_numels[ec]
            ss = slice_shapes[ec]
            parts = [
                g[rank_offsets[ec][r]:
                  rank_offsets[ec][r] + rows[r] * sn].reshape(
                      (rows[r],) + ss)
                for r in range(size)]
            e.output = jax.device_put(
                jnp.concatenate(parts, axis=0) if size > 1
                else parts[0])
        return self._complete(entries)

    # -- broadcast (ncclBcast role, two renderings) ----------------------
    def execute_broadcast(self, entries, response: Response) -> Status:
        """Fills the ncclBcast role (reference:
        nccl_operations.cc:334-351). Two renderings, selected by
        HOROVOD_XLA_BCAST (no native one-to-all collective exists at
        the jax level — ppermute forbids multicast sources):

        * ``psum`` (default): mask to the root's contribution and
          psum. One fused, pipelined collective; ~2x payload per link
          (allreduce bandwidth) but single-round. Measured fastest on
          8-way worlds (benchmarks/collective_bench.py
          broadcast_rendering).
        * ``tree``: binary-tree ppermute chain; every device receives
          the payload exactly once (N-1 transfers over the fabric vs
          the psum's ~2N) at ceil(log2 N) sequential rounds of
          latency. Wins on small worlds / congested fabrics.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        (entry,) = entries
        x = entry.tensor
        root = entry.root_rank
        size = self._size_fn()
        flat = jnp.ravel(x)  # 0-d scalars are legal for broadcast
        rendering = getattr(self._config, "xla_broadcast", "psum") \
            if self._config is not None else "psum"

        if rendering == "tree":
            def body(t):
                idx = jax.lax.axis_index(_AXIS)
                v = (idx - root) % size  # virtual index: root is 0
                cur = t
                k = 1
                while k < size:
                    perm = [((u + root) % size, (u + k + root) % size)
                            for u in range(k) if u + k < size]
                    received = jax.lax.ppermute(cur, _AXIS, perm=perm)
                    cur = jnp.where((v >= k) & (v < 2 * k), received,
                                    cur)
                    k *= 2
                return cur

            out = self._run_shard_op("broadcast", flat, P(_AXIS), body,
                                     extra=(root, "tree"),
                                     response=response)
        else:
            def body(t):
                idx = jax.lax.axis_index(_AXIS)
                contrib = jnp.where(idx == root, t, jnp.zeros_like(t))
                return jax.lax.psum(contrib, _AXIS)

            out = self._run_shard_op("broadcast", flat, P(), body,
                                     extra=(root, "psum"),
                                     response=response)
        entry.output = jax.device_put(
            out.addressable_data(0).reshape(x.shape))
        return self._complete(entries)

    # -- alltoall --------------------------------------------------------
    def execute_alltoall(self, entries, response: Response) -> Status:
        import jax
        from jax.sharding import PartitionSpec as P

        (entry,) = entries
        x = entry.tensor

        def body(t):
            # tiled all_to_all: split dim 0 into `size` blocks, exchange,
            # re-concatenate along dim 0 — block d of the output came
            # from rank d.
            return jax.lax.all_to_all(t, _AXIS, split_axis=0,
                                      concat_axis=0, tiled=True)

        out = self._run_shard_op("alltoall", x, P(_AXIS), body,
                                 response=response)
        entry.output = jax.device_put(out.addressable_data(0))
        return self._complete(entries)

    # -- reducescatter ---------------------------------------------------
    def execute_reducescatter(self, entries, response: Response) -> Status:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.compat import jaxshim

        (entry,) = entries
        x = entry.tensor
        size = self._size_fn()
        pre, post = response.prescale_factor, response.postscale_factor

        def body(t):
            if pre != 1.0:
                t = t * jnp.asarray(pre, t.dtype)
            y = jaxshim.psum_scatter(
                t.reshape((size, t.shape[0] // size) + t.shape[1:]),
                _AXIS, scatter_dimension=0, tiled=False)
            if post != 1.0:
                y = y * jnp.asarray(post, y.dtype)
            return y

        out = self._run_shard_op("reducescatter", x, P(_AXIS), body,
                                 extra=(pre, post), response=response)
        entry.output = jax.device_put(out.addressable_data(0))
        return self._complete(entries)

    def execute_barrier(self, entries, response: Response) -> Status:
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        import jax

        def body(t):
            return jax.lax.psum(t, _AXIS)

        self._run_shard_op("barrier", jnp.zeros((1,), jnp.float32),
                           P(), body).block_until_ready()
        return Status.OK()


class IciPlane:
    """Pre-compiled fused-psum steady cycle over the local device mesh
    (HOROVOD_TPU_ICI): the intra-slice leg of the ALG_ICI verdict.

    The PR 3 fused speculative cycle packs each steady bucket on the
    HOST — numpy concat, prescale multiply, wire-dtype cast — every
    step. This plane lowers that whole pack to ONE jitted fused-psum
    XLA executable per (cache epoch, steady mask, wire dtype, segment
    signature): each local device prescales and casts its shard of the
    bucket, writes it at its own offset into a zero-filled wire buffer,
    and a psum over the ``ici`` axis assembles the contiguous wire
    payload (zeros elsewhere make the sum an exact identity). On a
    real pod slice the SAME program's psum is what performs the
    gradient reduce — :meth:`fused_reduce_partials` runs it over
    per-device partial contributions; on the forced-host-platform CI
    mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=N``) the
    shards are lanes of the pack pipeline and the psum is pure
    assembly, so results stay bit-exact with the socket plane's numpy
    pack. Either way the host sees ONE wire buffer, already in the
    negotiated wire dtype, that rides the existing compressed
    socket/ring plane for the cross-slice (DCN) leg — negotiation
    never leaves the coordinator's one-round-trip cached path.

    Executables are the analog of common/steady.py's SteadyPlan: built
    once per signature, replayed every steady cycle, evicted on the
    ResponseCache epoch bump (world-replicated plan state — the epoch
    only moves on broadcast verdicts, which hvdlint's world-coherence
    analyzer enforces on :meth:`note_cache_epoch`)."""

    # Wire dtypes the fused executable can cast to on-device. int8 is
    # excluded for the same reason the speculative cycle excludes it:
    # its per-rank scale header cannot ride the inline coordinator
    # reduce.
    _WIRES = (0, 1, 2)  # WIRE_NONE, WIRE_BF16, WIRE_FP16

    def __init__(self, max_devices: int = 0):
        self._max_devices = max(0, int(max_devices))
        self._lock = lockdep.lock("xla_ops.IciPlane._lock")
        self._mesh = None
        self._ndev = 0
        self._cache: Dict[Tuple, object] = {}
        # Epoch-coupled plan state, moved only by the broadcast cache
        # epoch (note_cache_epoch).
        self._epoch = -1  # hvdlint: world-replicated
        self.compiles = 0
        self.cycles = 0
        self._m_compiles = None
        self._m_cycles = None
        self._m_bytes = None

    # -- capability ------------------------------------------------------
    def probe(self) -> bool:
        """This rank's view only — the runtime feeds it through
        controller.agree() so a world with one mesh-less rank degrades
        to the socket plane everywhere, together."""
        try:
            import jax

            from horovod_tpu.compat import jaxshim
            devs = sorted(jax.local_devices(), key=lambda d: d.id)
            if self._max_devices:
                devs = devs[:self._max_devices]
            if len(devs) < 2:
                hlog.debug(
                    f"ICI plane unavailable: {len(devs)} local "
                    "device(s); need >= 2 (set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=N for a "
                    "CPU-mesh CI run)")
                return False
            self._mesh = jaxshim.make_raw_mesh(np.array(devs),
                                               (_ICI_AXIS,))
            self._ndev = len(devs)
            return True
        except Exception as e:
            hlog.debug(f"ICI plane unavailable: {e}")
            return False

    @property
    def ndev(self) -> int:
        return self._ndev

    def attach_metrics(self, registry) -> None:
        self._m_compiles = registry.counter(
            "hvd_ici_compiles_total",
            "fused-psum executables built for the ICI plane (flat "
            "after warmup when the steady cycle is riding the cache)")
        self._m_cycles = registry.counter(
            "hvd_ici_cycles_total",
            "steady fused segments packed/reduced on the ICI mesh")
        # The mesh leg's share of the per-backend byte totals (same
        # family as hvd_backend_bytes_total{backend="xla_mesh"/...}).
        self._m_bytes = registry.counter(
            'hvd_backend_bytes_total{backend="ici_mesh"}',
            "payload bytes moved through the ICI mesh leg")

    @world_coherent
    def note_cache_epoch(self, epoch: int) -> None:
        """Evict compiled plans of a superseded ResponseCache epoch.
        Called at the same broadcast-driven stream position on every
        rank (the epoch is a pure function of the coordinator's
        verdicts), so the plan state never diverges."""
        with self._lock:
            if epoch != self._epoch:
                self._epoch = epoch
                self._cache.clear()

    # -- compiled fused-psum cycle ---------------------------------------
    def _compiled(self, key, builder):
        with self._lock:
            fn = self._cache.get(key)
            if fn is None:
                fn = builder()
                self._cache[key] = fn
                self.compiles += 1
                if self._m_compiles is not None:
                    self._m_compiles.inc()
        return fn

    @staticmethod
    def _np_wire(wire):
        from horovod_tpu.common import wire_dtype as _wd
        return _wd.wire_np_dtype(wire)

    def fused_pack(self, sig, flat, prescale: float, wire: int):
        """Pack one steady segment through the pre-compiled fused-psum
        executable: ``flat`` (1-D host array, the segment's concat) is
        scattered one shard per device, each shard is prescaled and
        cast to the wire dtype ON DEVICE, and the psum assembles the
        contiguous wire buffer. Returns a writable host array in the
        wire dtype, byte-compatible with SteadyPlan.pack's output for
        the same segment, or None when this segment cannot ride the
        mesh (no mesh, unsupported dtype/wire) — the caller falls back
        to the host pack.

        ``sig`` is (cache_epoch, steady_mask, segment_index): with the
        shapes/dtypes below it forms the one-executable-per-signature
        key the steady cycle replays."""
        if self._mesh is None or wire not in self._WIRES:
            return None
        if flat.dtype not in (np.float32, np.float64):
            return None
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.compat import jaxshim

        if flat.dtype == np.float64 and not jax.config.jax_enable_x64:
            # device_put would silently canonicalize f64 down to f32
            # and the adopted buffer could never be byte-compatible
            # with the plan — decline BEFORE paying the transfer.
            return None
        n = int(flat.size)
        if n == 0:
            return None
        ndev = self._ndev
        shard = -(-n // ndev)  # ceil
        n_pad = shard * ndev
        out_dtype = self._np_wire(wire) if wire else flat.dtype
        key = (sig, n, str(flat.dtype), wire, float(prescale))

        def build():
            def body(x):
                if prescale != 1.0:
                    x = x * jnp.asarray(prescale, x.dtype)
                if wire:
                    x = x.astype(out_dtype)
                idx = jax.lax.axis_index(_ICI_AXIS)
                buf = jnp.zeros((n_pad,), x.dtype)
                buf = jax.lax.dynamic_update_slice(
                    buf, x, (idx * shard,))
                # Every position holds exactly one device's shard and
                # zeros from the others — x + 0 is exact, so the psum
                # is pure assembly here and becomes the gradient
                # reduce when the axis spans a real pod slice.
                return jax.lax.psum(buf, _ICI_AXIS)

            m = _shard_map(body, self._mesh, P(_ICI_AXIS), P())
            return jax.jit(m)

        fn = self._compiled(key, build)
        if n_pad != n:
            padded = np.zeros((n_pad,), flat.dtype)
            padded[:n] = flat
        else:
            padded = flat
        garr = jax.device_put(
            padded, jaxshim.named_sharding(self._mesh, P(_ICI_AXIS)))
        out = fn(garr)
        host = np.asarray(jax.device_get(out.addressable_data(0)))
        res = host[:n]
        if not res.flags.writeable:
            # The coordinator reduces peers INTO its own buffer; the
            # device fetch may hand back a read-only view.
            res = res.copy()
        self.cycles += 1
        if self._m_cycles is not None:
            self._m_cycles.inc()
            self._m_bytes.inc(res.nbytes)
        return res

    def fused_reduce_partials(self, sig, partials, prescale: float,
                              wire: int):
        """Pod-mode variant: ``partials`` is [ndev, n] — one partial
        gradient contribution per local device — and the psum REDUCES
        across the axis instead of assembling shards. Each device
        prescales and casts its row to the wire dtype first, so the
        sum happens in wire precision exactly like the coordinator's
        cross-slice reduce (common/wire_dtype.py reduce_peer_payloads).
        Returns the reduced wire-dtype host row, or None when the
        plane cannot carry it."""
        if self._mesh is None or wire not in self._WIRES:
            return None
        partials = np.ascontiguousarray(partials)
        if partials.ndim != 2 or partials.shape[0] != self._ndev:
            return None
        if partials.dtype not in (np.float32, np.float64):
            return None
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.compat import jaxshim

        if partials.dtype == np.float64 \
                and not jax.config.jax_enable_x64:
            return None  # canonicalization would change the bytes
        n = int(partials.shape[1])
        out_dtype = self._np_wire(wire) if wire else partials.dtype
        key = (sig, "partials", n, str(partials.dtype), wire,
               float(prescale))

        def build():
            def body(x):
                x = x.reshape((n,))
                if prescale != 1.0:
                    x = x * jnp.asarray(prescale, x.dtype)
                if wire:
                    x = x.astype(out_dtype)
                return jax.lax.psum(x, _ICI_AXIS)

            m = _shard_map(body, self._mesh, P(_ICI_AXIS), P())
            return jax.jit(m)

        fn = self._compiled(key, build)
        garr = jax.device_put(
            partials, jaxshim.named_sharding(self._mesh, P(_ICI_AXIS)))
        out = fn(garr)
        host = np.asarray(jax.device_get(out.addressable_data(0)))
        if not host.flags.writeable:
            host = host.copy()
        self.cycles += 1
        if self._m_cycles is not None:
            self._m_cycles.inc()
            self._m_bytes.inc(host.nbytes)
        return host
