"""JAX framework adapter — the flagship user API.

Role-equivalent of the reference's per-framework adapters
(reference: horovod/tensorflow/__init__.py, horovod/torch/__init__.py):
basics re-exported, collective ops on framework tensors, an optimizer
wrapper that averages gradients across workers, and parameter/optimizer
state broadcast for checkpoint-restore symmetry (SURVEY §5
checkpoint/resume pattern).

Two gradient-sync paths, chosen by where your step runs:

- **in-jit (recommended on TPU)**: ``DistributedOptimizer(tx)`` wraps an
  optax GradientTransformation; inside a shard_map/pjit step it pmeans
  gradients over the mesh axis before the update — the role of the
  reference's DistributedOptimizer.compute_gradients override
  (reference: horovod/tensorflow/__init__.py:219-233), done where XLA
  can fuse and overlap it.
- **out-of-jit**: ``allreduce_gradients_async`` stages host gradients
  through the background runtime (negotiation, fusion, timeline — the
  full Horovod contract) — the role of torch's grad-hook + synchronize
  flow (reference: horovod/torch/__init__.py:95-147).
"""

from __future__ import annotations

from typing import Any

import numpy as np

# Basics + host-side ops (same surface as the reference adapters
# re-exporting HorovodBasics, reference: horovod/tensorflow/__init__.py:36-43)
from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous,
)
from horovod_tpu.ops import (  # noqa: F401
    allreduce, allreduce_async, grouped_allreduce,
    grouped_allreduce_async, allgather, allgather_async,
    broadcast, broadcast_async, alltoall, alltoall_async,
    reducescatter, reducescatter_async, barrier, poll, synchronize,
    Average, Sum,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu import spmd as _spmd
from horovod_tpu.spmd import (  # noqa: F401
    zero_optimizer, zero_state_specs, sharded_clip_by_global_norm,
)


def DistributedOptimizer(tx, op: int = _spmd.Average,
                         axis="data", compression=Compression.none,
                         gradient_predivide_factor: float = 1.0):
    """Wrap an optax GradientTransformation so each ``update`` first
    averages gradients over the mesh ``axis`` (in-jit) — the optax
    rendering of the reference's DistributedOptimizer contract
    (reference: horovod/tensorflow/__init__.py:151-249). Use inside a
    shard_map/pjit-traced step with ``axis`` in scope; under a plain
    jit (GSPMD) you don't need it at all — replicated params + sharded
    batch already imply the gradient all-reduce."""
    import optax

    def init_fn(params):
        return tx.init(params)

    def update_fn(grads, state, params=None, **extra):
        if gradient_predivide_factor != 1.0 and op == _spmd.Average:
            # Reference semantics (horovod allreduce prescale/postscale):
            # prescale by 1/f before the sum, postscale by f/size after —
            # net effect is still the mean, but intermediate magnitudes
            # shrink for numerical headroom.
            f = gradient_predivide_factor

            def averaged(g):
                n = _spmd.mesh_size(axis)
                return _spmd.allreduce(g, op=_spmd.Sum, axis=axis,
                                       prescale_factor=1.0 / f,
                                       postscale_factor=f / n)

            import jax
            if compression is not Compression.none:
                def one(g):
                    c, ctx = compression.compress(g)
                    return compression.decompress(averaged(c), ctx)
            else:
                one = averaged
            grads = jax.tree_util.tree_map(one, grads)
        else:
            grads = _spmd.allreduce_gradients(grads, op=op, axis=axis,
                                              compression=compression)
        return tx.update(grads, state, params, **extra)

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def allreduce_gradients(grads, op: int = Average,
                        compression=Compression.none):
    """Synchronously average a host-side gradient pytree through the
    background runtime (negotiation + fusion + timeline) — the
    reference's hook-then-synchronize flow
    (reference: horovod/torch/__init__.py:95-147).

    The uncompressed path submits the leaves as ONE grouped
    allreduce, which the overlap tier (HOROVOD_OVERLAP_BUCKETS /
    HOROVOD_OVERLAP_BYTES, docs/performance.md Layer 5) splits into
    ready-order buckets: jax gradient leaves are futures, so early
    buckets negotiate and ride the wire while backward compute for
    later leaves is still running, and the tail ``synchronize`` drain
    only ever blocks on the last bucket."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if compression is Compression.none:
        handles = grouped_allreduce_async(leaves, name="grad", op=op)
        outs = [synchronize(h) for h in handles]
        return jax.tree_util.tree_unflatten(treedef, outs)
    handles = []
    for i, g in enumerate(leaves):
        comp, ctx = compression.compress(g)
        handles.append((allreduce_async(comp, name=f"grad.{i}", op=op),
                        ctx))
    outs = [compression.decompress(synchronize(h), ctx)
            for h, ctx in handles]
    return jax.tree_util.tree_unflatten(treedef, outs)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` through the
    runtime (reference: horovod/torch/__init__.py:200-229
    broadcast_parameters). Out-of-jit; for the in-jit form use
    horovod_tpu.spmd.broadcast_variables."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    handles = [broadcast_async(p, root_rank=root_rank, name=f"bcast.p.{i}")
               for i, p in enumerate(leaves)]
    outs = [synchronize(h) for h in handles]
    return jax.tree_util.tree_unflatten(treedef, outs)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optax optimizer state (an arbitrary pytree whose
    non-array leaves are left alone) — the reference's
    broadcast_optimizer_state incl. scalar wrapping
    (reference: horovod/torch/__init__.py:232-348)."""
    import jax

    def is_arr(x):
        return isinstance(x, (np.ndarray, np.generic)) or \
            type(x).__module__.startswith("jax")

    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    handles = []
    for i, leaf in enumerate(leaves):
        if is_arr(leaf):
            # 0-d arrays (step counters) broadcast like everything else —
            # the reference's scalar-wrapping dance is unnecessary here.
            handles.append(
                (i, broadcast_async(leaf, root_rank=root_rank,
                                    name=f"bcast.os.{i}")))
    out = list(leaves)
    for i, h in handles:
        res = synchronize(h)
        # preserve original leaf type/dtype for int steps
        orig = leaves[i]
        if isinstance(orig, np.ndarray):
            res = np.asarray(res, dtype=orig.dtype).reshape(orig.shape)
        out[i] = res
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_train_state(state: Any, root_rank: int = 0):
    """Broadcast a whole train state (e.g. flax TrainState or the dicts
    produced by horovod_tpu.parallel.Trainer) from root_rank."""
    return broadcast_parameters(state, root_rank=root_rank)


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "is_homogeneous",
    "allreduce", "allreduce_async", "grouped_allreduce",
    "grouped_allreduce_async", "allgather", "allgather_async",
    "broadcast", "broadcast_async", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "barrier", "poll",
    "synchronize", "Average", "Sum", "Compression",
    "DistributedOptimizer", "allreduce_gradients",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_train_state", "zero_optimizer", "zero_state_specs",
    "sharded_clip_by_global_norm",
]
