"""MXNet adapter (reference: horovod/mxnet/__init__.py:1-140,
horovod/mxnet/mpi_ops.py).

Provided for API parity; requires mxnet (not bundled on TPU images).
NDArrays are staged through numpy into the background runtime, like
the torch adapter — the reference's MXTempBufferShared CudaOnCPU
staging path (reference: horovod/mxnet/adapter.cc), which is the only
mode that makes sense on a TPU host.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu import ops as _ops
from horovod_tpu.ops import Average, Sum  # noqa: F401


def _require_mx():
    try:
        import mxnet
        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet requires mxnet; on TPU hosts prefer "
            "horovod_tpu.jax.") from e


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    mx = _require_mx()
    out = _ops.allreduce(tensor.asnumpy(),
                         op=Average if average else Sum, name=name)
    return mx.nd.array(np.asarray(out), dtype=tensor.dtype)


def allreduce_(tensor, average: bool = True, name: Optional[str] = None):
    result = allreduce(tensor, average=average, name=name)
    tensor[:] = result
    return tensor


def allgather(tensor, name: Optional[str] = None):
    mx = _require_mx()
    out = _ops.allgather(tensor.asnumpy(), name=name)
    return mx.nd.array(np.asarray(out), dtype=tensor.dtype)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    mx = _require_mx()
    out = _ops.broadcast(tensor.asnumpy(), root_rank=root_rank, name=name)
    return mx.nd.array(np.asarray(out), dtype=tensor.dtype)


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None):
    tensor[:] = broadcast(tensor, root_rank=root_rank, name=name)
    return tensor


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a gluon ParameterDict / dict of NDArrays from root
    (reference: horovod/mxnet/__init__.py:96-140 incl. deferred-init
    handling: parameters not yet initialized are skipped here — call
    again after ``net.initialize()``)."""
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        items = list(enumerate(params))
    for name, p in items:
        try:
            data = p.data() if hasattr(p, "data") else p
        except Exception:
            continue  # deferred init
        broadcast_(data, root_rank=root_rank, name=f"bcast.{name}")


class DistributedOptimizer:
    """Wrap an mxnet optimizer: allreduce grads in update()
    (reference: horovod/mxnet/__init__.py:38-70)."""

    def __init__(self, optimizer):
        self._opt = optimizer

    def _do(self, index, weight, grad, state, update_fn):
        if size() > 1:
            # Aggregated updates pass lists (reference:
            # horovod/mxnet/__init__.py _do_allreduce list branch).
            if isinstance(index, (tuple, list)):
                for i, g in zip(index, grad):
                    allreduce_(g, average=True, name=f"grad.{i}")
            else:
                allreduce_(grad, average=True, name=f"grad.{index}")
        update_fn(index, weight, grad, state)

    def update(self, index, weight, grad, state):
        self._do(index, weight, grad, state, self._opt.update)

    def update_multi_precision(self, index, weight, grad, state):
        self._do(index, weight, grad, state,
                 self._opt.update_multi_precision)

    def __getattr__(self, item):
        return getattr(self._opt, item)


class DistributedTrainer:
    """gluon Trainer whose _allreduce_grads averages over ranks
    (reference: horovod/mxnet/__init__.py:79-92)."""

    def __new__(cls, params, optimizer, optimizer_params=None):
        mx = _require_mx()
        if isinstance(optimizer, DistributedOptimizer):
            # Unwrap: the trainer already averages in _allreduce_grads;
            # a wrapped optimizer would reduce twice (reference:
            # horovod/mxnet/__init__.py:81-84).
            optimizer = optimizer._opt

        class _Trainer(mx.gluon.Trainer):
            def __init__(self, params, optimizer, optimizer_params):
                super().__init__(params, optimizer, optimizer_params,
                                 kvstore=None)
                self._scale /= size()

            def _allreduce_grads(self):
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        for g in param.list_grad():
                            allreduce_(g, average=False,
                                       name=f"grad.{i}")

        return _Trainer(params, optimizer, optimizer_params)


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "Average", "Sum", "Compression",
    "allreduce", "allreduce_", "allgather", "broadcast", "broadcast_",
    "broadcast_parameters", "DistributedOptimizer", "DistributedTrainer",
]
