"""Spark integration (reference: horovod/spark/__init__.py:82-199).

``horovod_tpu.spark.run(fn, ...)`` runs ``fn`` on ``num_proc`` Spark
tasks with the horovod_tpu world wired up, returning results ordered
by rank. Requires pyspark; without it, ``horovod_tpu.run.api.run``
provides the identical contract on local processes.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, List, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark. For the same "
            "contract without Spark use horovod_tpu.run.api.run(fn, "
            "num_proc=N).") from e


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None,
        start_timeout: float = 60.0, verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks
    (reference: spark/__init__.py:82-199). Each task initializes a
    horovod_tpu world whose rank order follows Spark partition ids,
    rank 0's host carrying the coordinator — the reference's host-hash
    grouping with rank 0 first (spark/__init__.py:144-154)."""
    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(sc.defaultParallelism, 1)

    # Stage 1: elect the coordinator — partition 0 reports a reachable
    # address and a reserved port through the driver.
    from horovod_tpu.run.services import local_addresses
    from horovod_tpu.common import network

    def _elect(index, _it):
        if index == 0:
            srv = network.listen(0)
            port = srv.getsockname()[1]
            addr = local_addresses()[0]
            srv.close()  # released; rank 0 rebinds at init
            yield (addr, port)

    coord_addr, coord_port = sc.parallelize(
        range(num_proc), num_proc).mapPartitionsWithIndex(
            _elect).collect()[0]

    secret = os.environ.get("HOROVOD_SECRET_KEY", "")

    # Stage 2: run fn on every partition with the world wired up.
    def _task(index, _it):
        os.environ["HOROVOD_RANK"] = str(index)
        os.environ["HOROVOD_SIZE"] = str(num_proc)
        os.environ["HOROVOD_CONTROLLER_ADDR"] = coord_addr
        os.environ["HOROVOD_CONTROLLER_PORT"] = str(coord_port)
        os.environ["HOROVOD_START_TIMEOUT"] = str(start_timeout)
        if secret:
            os.environ["HOROVOD_SECRET_KEY"] = secret
        import horovod_tpu as hvd
        hvd.init()
        try:
            result = fn(*args, **kwargs)
        finally:
            hvd.shutdown()
        yield (index, result)

    results = sc.parallelize(range(num_proc), num_proc) \
        .mapPartitionsWithIndex(_task).collect()
    # ordered by rank (reference: spark/__init__.py:195-199)
    return [r for _, r in sorted(results)]


__all__ = ["run"]
