"""Spark integration (reference: horovod/spark/__init__.py:82-199).

``horovod_tpu.spark.run(fn, ...)`` runs ``fn`` on ``num_proc`` Spark
tasks with the horovod_tpu world wired up, returning results ordered
by rank. Requires pyspark; without it, ``horovod_tpu.run.api.run``
provides the identical contract on local processes.

Startup shape mirrors the reference's driver service
(reference: spark/driver/driver_service.py + spark/__init__.py:122-161):
the Spark *driver* hosts a small rendezvous TCP service; every task
registers with it, and the task holding partition 0 binds the
coordinator listener FIRST and publishes the bound endpoint — the
socket is handed straight to ``hvd.init`` (never closed and rebound),
so the published port cannot be stolen in between. Each task also runs
a parent-death watchdog (reference: spark/task/mpirun_exec_fn.py:26-38)
so orphaned ranks exit instead of hanging the job.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Callable, List, Optional

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network

_TAG_RDV = 9


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark. For the same "
            "contract without Spark use horovod_tpu.run.api.run(fn, "
            "num_proc=N).") from e


def _start_parent_watchdog(poll_s: float = 1.0) -> threading.Thread:
    """Kill this process when its parent (the Spark executor) dies
    (reference: spark/task/mpirun_exec_fn.py:26-38). Reparenting to
    init/subreaper changes os.getppid(); an orphaned rank would
    otherwise sit in a collective forever and stall the world."""
    parent = os.getppid()

    def _watch():
        while True:
            time.sleep(poll_s)
            if os.getppid() != parent:
                hlog.warning("parent process died; exiting rank")
                os._exit(1)

    t = threading.Thread(target=_watch, name="hvd-parent-watchdog",
                         daemon=True)
    t.start()
    return t


class _Rendezvous:
    """Driver-side endpoint exchange: partition 0 publishes the bound
    coordinator endpoint; every task receives it. One thread, framed
    HMAC channels — same transport as the control plane."""

    def __init__(self, num_proc: int, secret: bytes = b""):
        self._num = num_proc
        self._secret = secret
        self._server = network.listen(0)
        self.port = self._server.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        waiting = []
        controller = None
        served = 0
        self._server.settimeout(1.0)
        while served < self._num:
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                ch = network.Channel(sock, self._secret)
                tag, payload = ch.recv()
                if tag != _TAG_RDV:
                    raise ConnectionError(f"unexpected tag {tag}")
                hello = json.loads(bytes(payload).decode())
                if "controller" in hello:
                    controller = hello["controller"]
            except (ConnectionError, OSError, ValueError, KeyError) as e:
                hlog.warning(f"spark rendezvous rejected connection: {e}")
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            waiting.append(ch)
            if controller is not None:
                blob = json.dumps({"controller": controller}).encode()
                for w in waiting:
                    try:
                        w.send(blob, _TAG_RDV)
                        w.close()
                    except OSError:
                        pass
                    served += 1
                waiting = []

    def close(self) -> None:
        try:
            self._server.close()
        except OSError:
            pass


def _exchange(driver_addr: str, driver_port: int, secret: bytes,
              controller: Optional[dict],
              timeout: float) -> dict:
    """Task half of the rendezvous: publish (partition 0) or fetch."""
    ch = network.connect(driver_addr, driver_port, secret,
                         timeout=timeout, retry_deadline=timeout)
    hello = {} if controller is None else {"controller": controller}
    ch.send(json.dumps(hello).encode(), _TAG_RDV)
    # Bound the wait for partition 0's publication: without this a
    # straggling/unreachable partition 0 would leave every other task
    # in an unbounded blocking recv (network.connect clears the socket
    # timeout after connecting).
    ch.sock.settimeout(timeout)
    tag, payload = ch.recv()
    if tag != _TAG_RDV:
        raise ConnectionError(f"unexpected rendezvous tag {tag}")
    ch.close()
    return json.loads(bytes(payload).decode())["controller"]


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None,
        start_timeout: float = 60.0, verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks
    (reference: spark/__init__.py:82-199). Each task initializes a
    horovod_tpu world whose rank order follows Spark partition ids,
    rank 0's host carrying the coordinator — the reference's host-hash
    grouping with rank 0 first (spark/__init__.py:144-154)."""
    _require_pyspark()
    from pyspark.sql import SparkSession

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(sc.defaultParallelism, 1)

    from horovod_tpu.run.services import local_addresses

    secret_str = hconfig.env_str("HOROVOD_SECRET_KEY", "")
    secret = secret_str.encode()
    rendezvous = _Rendezvous(num_proc, secret)
    driver_addr = local_addresses()[0]
    driver_port = rendezvous.port

    def _task(index, _it):
        _start_parent_watchdog()
        listener = None
        if index == 0:
            # Bind FIRST, publish the bound endpoint, and hand the very
            # same socket to init — no close/rebind window.
            listener = network.listen(0)
            controller = {"addr": local_addresses()[0],
                          "port": listener.getsockname()[1]}
        else:
            controller = None
        controller = _exchange(driver_addr, driver_port, secret,
                               controller, start_timeout)
        os.environ["HOROVOD_RANK"] = str(index)
        os.environ["HOROVOD_SIZE"] = str(num_proc)
        os.environ["HOROVOD_CONTROLLER_ADDR"] = controller["addr"]
        os.environ["HOROVOD_CONTROLLER_PORT"] = str(controller["port"])
        os.environ["HOROVOD_START_TIMEOUT"] = str(start_timeout)
        if secret_str:
            os.environ["HOROVOD_SECRET_KEY"] = secret_str
        import horovod_tpu as hvd
        from horovod_tpu.common import basics
        basics.init(coordinator_listener=listener)
        try:
            result = fn(*args, **kwargs)
        finally:
            hvd.shutdown()
        yield (index, result)

    try:
        results = sc.parallelize(range(num_proc), num_proc) \
            .mapPartitionsWithIndex(_task).collect()
    finally:
        rendezvous.close()
    # ordered by rank (reference: spark/__init__.py:195-199)
    return [r for _, r in sorted(results)]


__all__ = ["run"]
