"""Parallelism beyond the reference's data parallelism.

The reference implements exactly one strategy — synchronous data
parallelism via allreduce (reference: horovod/tensorflow/__init__.py:151
DistributedOptimizer; SURVEY §2.3) — and no sequence/long-context
support at all. These are first-class here:

- ``sharding``        — rule-based parameter sharding (tensor parallelism)
- ``ring_attention``  — sequence/context parallelism for long sequences
- ``trainer``         — composes dp x tp x sp into one jitted train step
"""

from horovod_tpu.parallel.sharding import (
    ShardingRules, infer_sharding, transformer_tp_rules,
)
from horovod_tpu.parallel.ring_attention import (
    ring_attention, make_ring_attention,
)
from horovod_tpu.parallel.trainer import Trainer, TrainerConfig

__all__ = [
    "ShardingRules", "infer_sharding", "transformer_tp_rules",
    "ring_attention", "make_ring_attention",
    "Trainer", "TrainerConfig",
]
