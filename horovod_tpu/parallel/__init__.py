"""Parallelism beyond the reference's data parallelism.

The reference implements exactly one strategy — synchronous data
parallelism via allreduce (reference: horovod/tensorflow/__init__.py:151
DistributedOptimizer; SURVEY §2.3) — and no sequence/long-context
support at all. These are first-class here:

- ``sharding``        — rule-based parameter sharding (tensor + expert
                        parallelism)
- ``ring_attention``  — sequence/context parallelism for long sequences
- ``pipeline``        — GPipe-style pipeline parallelism over a mesh axis
- ``trainer``         — composes dp x tp x sp x ep into one jitted step
"""

from horovod_tpu.parallel.sharding import (
    ShardingRules, fsdp_sharding, infer_sharding, transformer_tp_rules,
)
from horovod_tpu.parallel.ring_attention import (
    ring_attention, make_ring_attention,
)
from horovod_tpu.parallel.ulysses import (
    make_ulysses_attention, ulysses_attention,
)
from horovod_tpu.parallel.pipeline import (
    make_pipeline_apply, pipeline_stages,
)
from horovod_tpu.parallel.trainer import (
    Trainer, TrainerConfig, make_chunked_lm_loss,
)


def __getattr__(name):
    # Lazy: pipelined_lm pulls in flax (an optional extra); the rest of
    # this package must stay importable with jax alone.
    if name == "PipelinedLM":
        from horovod_tpu.parallel.pipelined_lm import PipelinedLM
        return PipelinedLM
    raise AttributeError(name)

__all__ = [
    "ShardingRules", "fsdp_sharding", "infer_sharding",
    "transformer_tp_rules",
    "ring_attention", "make_ring_attention",
    "ulysses_attention", "make_ulysses_attention",
    "pipeline_stages", "make_pipeline_apply", "PipelinedLM",
    "Trainer", "TrainerConfig", "make_chunked_lm_loss",
]
