"""All-to-all (Ulysses-style) sequence/context parallelism.

The second of the two standard long-context decompositions (the first,
ring attention, lives in ring_attention.py): instead of rotating kv
shards around the ring, one ``lax.all_to_all`` re-shards q/k/v from
sequence-sharded to HEAD-sharded, every device runs ordinary
full-sequence attention on its 1/p of the heads, and a second
all-to-all restores sequence sharding (the public DeepSpeed-Ulysses
pattern). Two a2a hops of S·H·D/p elements replace the ring's p-1
rotation steps — favorable when the head count divides the axis and
the interconnect prefers fewer, larger transfers; ring attention wins
when heads are scarce (H < p) or holding the full sequence per device
is the binding memory constraint. Causality is exact: each device sees
the whole sequence, so no cross-shard mask bookkeeping exists at all.
"""

from __future__ import annotations

from typing import Optional

from jax import lax


def ulysses_attention(q, k, v, *, axis: str = "seq",
                      causal: bool = True, attn_fn=None):
    """Inside shard_map: q, k, v [B, S/p, H, D] sequence-sharded over
    ``axis`` → full-sequence attention on H/p heads → [B, S/p, H, D].
    The head count must divide the axis size."""
    from horovod_tpu.compat import jaxshim
    p = jaxshim.axis_size(axis)
    heads = q.shape[2]
    if heads % p != 0:
        raise ValueError(
            f"ulysses attention needs heads ({heads}) divisible by the "
            f"'{axis}' axis size ({p}); use ring attention for H < p")
    if attn_fn is None:
        from horovod_tpu.models.transformer import best_attention
        attn_fn = best_attention

    import jax.numpy as jnp

    # One inbound all-to-all for all three tensors (stacked), one
    # outbound for the result: [.., B, S/p, H, D] -> [.., B, S, H/p, D];
    # tiled a2a concatenates received sequence blocks in rank order =
    # global order.
    stacked = jnp.stack([q, k, v])
    moved = lax.all_to_all(stacked, axis, split_axis=3, concat_axis=2,
                           tiled=True)
    out = attn_fn(moved[0], moved[1], moved[2], causal)
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                          tiled=True)


def make_ulysses_attention(mesh, data_axis: str = "data",
                           seq_axis: str = "seq",
                           attn_fn: Optional[object] = None):
    """Build an ``attention_fn`` for TransformerConfig that runs
    Ulysses sequence parallelism as a manual-sharding island inside an
    otherwise GSPMD-partitioned jit: batch over ``data_axis``, sequence
    over ``seq_axis``. Heads stay unsharded at the boundary (they are
    the exchange currency), so this composes with dp and — through the
    per-head split inside the island — occupies the role tensor
    parallelism plays for attention."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.ring_attention import \
        _cached_sharded_attention

    return _cached_sharded_attention(
        mesh, P(data_axis, seq_axis, None, None),
        lambda q, k, v, causal: ulysses_attention(
            q, k, v, axis=seq_axis, causal=causal, attn_fn=attn_fn))
