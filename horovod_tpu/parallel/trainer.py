"""Composed dp x tp x sp training — one jitted step over one mesh.

The reference's trainer story is "wrap your optimizer"
(reference: horovod/torch/__init__.py:42 DistributedOptimizer): the
gradient leaves the framework, is averaged by the runtime, and comes
back. The TPU-native story is stronger: parameters and batch carry
shardings, the step is jitted once over the mesh, and XLA inserts and
overlaps every collective (gradient all-reduce for dp, activation psum
for tp, kv-ring permutes for sp). This module is the composition point.

No manual gradient psum appears anywhere: with replicated parameters
and a dim-0-sharded batch, GSPMD derives the gradient all-reduce that
Horovod's whole background runtime exists to perform.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from horovod_tpu.parallel.sharding import (
    ShardingRules, fsdp_sharding, infer_sharding, transformer_tp_rules,
)


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    data_axis: str = "data"
    model_axis: Optional[str] = "model"   # None = no tensor parallelism
    seq_axis: Optional[str] = None        # None = no sequence parallelism
    expert_axis: Optional[str] = None     # None = no expert parallelism
    fsdp_axis: Optional[str] = None       # None = no parameter sharding
    # (fsdp_axis may equal data_axis: classic FSDP shards weights over
    # the data ranks; GSPMD inserts the per-layer all-gathers and the
    # gradient reduce-scatters, and optimizer state follows the
    # parameter shardings — see parallel.sharding.fsdp_sharding)
    # Sequence parallelism needs a ring attention_fn in the model config
    # (parallel.make_ring_attention) — injected there, not a flag here,
    # because the attention implementation lives in the module tree.
    donate_state: bool = True


class Trainer:
    """Builds init/step for a flax module over a mesh.

    ``loss_fn(apply_fn, params, batch) -> scalar`` defines the task;
    defaults to next-token LM loss on ``batch['tokens']``.
    """

    def __init__(self, module, mesh, tx,
                 config: TrainerConfig = TrainerConfig(),
                 rules: Optional[ShardingRules] = None,
                 loss_fn: Optional[Callable] = None,
                 batch_spec=None):
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.compat import jaxshim
        self.module = module
        self.mesh = mesh
        self.tx = tx
        self.config = config
        if rules is None:
            m = (config.model_axis
                 if config.model_axis
                 and config.model_axis in mesh.axis_names else None)
            ep = (config.expert_axis
                  if config.expert_axis
                  and config.expert_axis in mesh.axis_names else None)
            # EP works with or without TP: PartitionSpec treats a None
            # axis entry as replicated, so the rules compose naturally.
            rules = (transformer_tp_rules(m, expert_axis=ep)
                     if (m or ep) else ShardingRules([]))
        self.rules = rules
        self.loss_fn = loss_fn or _default_lm_loss
        if batch_spec is None:
            if config.seq_axis and config.seq_axis in mesh.axis_names:
                batch_spec = P(config.data_axis, config.seq_axis)
            else:
                batch_spec = P(config.data_axis)
        self.batch_sharding = jaxshim.named_sharding(mesh, batch_spec)
        self._step = None
        self._param_shardings = None

    # ------------------------------------------------------------------
    def init(self, rng, sample_batch):
        """Initialize params + opt state, already sharded per the rules."""
        batch = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self.batch_sharding), sample_batch)
        inputs = batch["tokens"] if isinstance(batch, dict) else batch

        params = jax.jit(self.module.init)(rng, inputs)
        self._param_shardings = infer_sharding(params, self.rules, self.mesh)
        fa = self.config.fsdp_axis
        if fa is not None:
            if fa not in self.mesh.axis_names:
                raise ValueError(
                    f"fsdp_axis {fa!r} is not a mesh axis "
                    f"{self.mesh.axis_names}; parameters would silently "
                    f"stay replicated")
            self._param_shardings = fsdp_sharding(
                params, self.mesh, axis=fa, base=self._param_shardings)
        params = jax.tree_util.tree_map(jax.device_put, params,
                                        self._param_shardings)
        # Optimizer moments must be co-sharded with their parameters
        # (XLA does not propagate input shardings through zeros_like, so
        # an unconstrained init would replicate them — forfeiting the
        # fsdp/tp memory win). Pin out_shardings by matching each state
        # leaf to its parameter via path suffix + shape.
        opt_shardings = _opt_state_shardings(
            self.tx, params, self._param_shardings, self.mesh)
        opt_state = jax.jit(self.tx.init,
                            out_shardings=opt_shardings)(params)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    # ------------------------------------------------------------------
    def step_fn(self):
        """The jitted train step (built once, cached)."""
        if self._step is not None:
            return self._step

        def step(state, batch):
            def loss_of(p):
                return self.loss_fn(self.module.apply, p, batch)
            loss, grads = jax.value_and_grad(loss_of)(state["params"])
            updates, new_opt = self.tx.update(grads, state["opt_state"],
                                              state["params"])
            import optax
            new_params = optax.apply_updates(state["params"], updates)
            return {"params": new_params, "opt_state": new_opt,
                    "step": state["step"] + 1}, loss

        donate = (0,) if self.config.donate_state else ()
        self._step = jax.jit(step, donate_argnums=donate)
        return self._step

    def train_step(self, state, batch):
        # device_put is a no-op for arrays already resident with an
        # equivalent sharding; host (numpy) batches are uploaded each
        # call — place a fixed batch on the mesh once yourself when
        # benchmarking (see examples/transformer_long_context.py: on
        # remote-attached TPUs the per-step upload dwarfs the step).
        batch = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self.batch_sharding), batch)
        return self.step_fn()(state, batch)


def _opt_state_shardings(tx, params, param_shardings, mesh):
    """NamedSharding tree for ``tx.init(params)``: param-shaped state
    leaves (Adam/momentum moments, keyed by the same sub-paths as the
    parameter tree) take their parameter's sharding; everything else
    (step counters, scalars) is replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.compat import jaxshim

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_sh = jax.tree_util.tree_leaves(
        param_shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    # Longest key first: "...['z']['w']" must win over a bare "...['w']"
    # when both are suffixes of a state leaf's path and shapes collide.
    keyed = sorted(
        ((jax.tree_util.keystr(path), leaf.shape, sh)
         for (path, leaf), sh in zip(flat, flat_sh)),
        key=lambda t: len(t[0]), reverse=True)

    abs_state = jax.eval_shape(tx.init, params)
    replicated = jaxshim.named_sharding(mesh, P())

    def one(path, leaf):
        ks = jax.tree_util.keystr(path)
        for pks, shape, sh in keyed:
            if ks.endswith(pks) and getattr(leaf, "shape", None) == shape:
                return sh
        return replicated

    return jax.tree_util.tree_map_with_path(one, abs_state)


_MOE_AUX_WEIGHT = 0.01  # Switch Transformer's alpha


def _lm_loss_with_moe_aux(apply_fn, params, batch, task_loss,
                          **apply_kwargs):
    """Shared LM-loss scaffolding: extract tokens, apply with sowed
    intermediates, add the Switch load-balancing auxiliary (zero for
    dense models). ``task_loss(output, tokens)`` computes the
    next-token loss from whatever ``apply_fn`` returned. Without the
    aux term a top-1 router collapses onto one expert and the fixed
    capacity silently drops the overflow tokens."""
    from horovod_tpu.models.transformer import moe_aux_loss
    tokens = batch["tokens"] if isinstance(batch, dict) else batch
    output, mutated = apply_fn(params, tokens,
                               mutable=["intermediates"],
                               **apply_kwargs)
    loss = task_loss(output, tokens)
    aux = moe_aux_loss(mutated.get("intermediates", {}))
    return loss + _MOE_AUX_WEIGHT * aux


def make_chunked_lm_loss(chunk: int = 1024):
    """Trainer ``loss_fn`` for big-vocab / long-context TransformerLM:
    next-token loss via :func:`models.transformer.lm_loss_from_hidden`,
    so the full [B, S, vocab] fp32 logits never exist in HBM. Same
    MoE-aux handling as the default loss.

    ``Trainer(model, mesh, tx, loss_fn=make_chunked_lm_loss(1024))``.
    """
    from horovod_tpu.models.transformer import lm_loss_from_hidden

    def loss_fn(apply_fn, params, batch):
        def task_loss(hidden, tokens):
            head_kernel = params["params"]["lm_head"]["kernel"]
            return lm_loss_from_hidden(hidden, head_kernel, tokens,
                                       chunk=chunk)
        return _lm_loss_with_moe_aux(apply_fn, params, batch,
                                     task_loss, return_hidden=True)

    return loss_fn


def _default_lm_loss(apply_fn, params, batch):
    """Next-token LM loss from full logits (see _lm_loss_with_moe_aux
    for the shared MoE-aux scaffolding)."""
    from horovod_tpu.models.transformer import lm_loss
    return _lm_loss_with_moe_aux(apply_fn, params, batch, lm_loss)

