"""GPipe-style pipeline parallelism over a mesh axis.

The reference scales batch only (SURVEY §2.3: no PP anywhere); this is
the TPU-native rendering of a pipeline: per-stage parameters live
stacked on a leading stage dimension sharded over the ``stage`` mesh
axis, microbatches stream through the stages with ``lax.ppermute``,
and the whole GPipe schedule is one ``lax.scan`` inside one jitted
``shard_map`` — XLA overlaps the per-tick compute with the
stage-to-stage transfer, and autodiff differentiates straight through
the scan + permutes, deriving the backward schedule for free (the
transpose of a ppermute is the reverse ppermute).

Contract: the model region being pipelined must be a stack of
structurally identical stages (the transformer's homogeneous block
tower). Embedding/head stay outside the pipelined region.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_stages(block_fn: Callable, stacked_params, x,
                    *, num_microbatches: int, axis: str = "stage"):
    """Run ``x`` through the pipeline. MUST be called inside a
    ``shard_map`` whose mesh has ``axis``; ``stacked_params`` is the
    per-device slice of the stage-stacked parameter pytree (leading
    stage dim of size 1 locally), ``x`` this shard's batch slice —
    replicated over the STAGE axis, and per-data-shard when composed
    with a data axis (make_pipeline_apply's ``data_axis``).

    ``block_fn(params, x) -> x`` applies one stage. Returns the full
    batch output, replicated across the stage axis.
    """
    n_stages = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)

    batch = x.shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} is not divisible by num_microbatches "
            f"{num_microbatches}")
    mb = batch // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    ticks = num_microbatches + n_stages - 1

    def tick(carry, t):
        out_buf, incoming = carry
        # stage 0 ingests microbatch t (clamped; garbage ticks are
        # never read back), other stages consume the permuted feed
        feed = jnp.where(
            idx == 0,
            micro[jnp.clip(t, 0, num_microbatches - 1)],
            incoming)
        y = block_fn(params, feed)
        # the last stage finished microbatch t - (n_stages - 1)
        m = t - (n_stages - 1)
        write = jnp.logical_and(idx == n_stages - 1,
                                jnp.logical_and(m >= 0,
                                                m < num_microbatches))
        slot = jnp.clip(m, 0, num_microbatches - 1)
        out_buf = jnp.where(
            write,
            lax.dynamic_update_index_in_dim(out_buf, y, slot, 0),
            out_buf)
        incoming = lax.ppermute(y, axis, perm)
        return (out_buf, incoming), None

    out0 = jnp.zeros_like(micro, dtype=x.dtype)
    in0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    (out_buf, _), _ = lax.scan(tick, (out0, in0), jnp.arange(ticks))

    # Only the last stage holds the result; psum of a masked buffer
    # broadcasts it to every stage (zeros elsewhere).
    out_buf = jnp.where(idx == n_stages - 1, out_buf,
                        jnp.zeros_like(out_buf))
    out_buf = lax.psum(out_buf, axis)
    return out_buf.reshape(x.shape)


def make_pipeline_apply(mesh, block_fn: Callable, *,
                        num_microbatches: int, axis: str = "stage",
                        data_axis: str = None):
    """jitted (stacked_params, x) -> y running the GPipe schedule over
    ``mesh``'s ``axis``. ``stacked_params`` leaves carry a leading
    stage dimension equal to the axis size.

    With ``data_axis`` set the batch dim additionally shards over that
    axis (dp x pp): each data shard streams its own microbatches
    through the stages, parameters stay replicated across the data
    axis, and shard_map's transpose inserts the gradient all-reduce
    over ``data_axis`` — no manual psum, same as the Trainer's dp
    story. ``num_microbatches`` must divide the per-data-shard batch."""
    from jax.sharding import PartitionSpec as P

    def apply(stacked_params, x):
        return pipeline_stages(block_fn, stacked_params, x,
                               num_microbatches=num_microbatches,
                               axis=axis)

    def shard_specs(tree):
        return jax.tree_util.tree_map(lambda _: P(axis), tree)

    batch_spec = P(data_axis) if data_axis else P()

    def run(stacked_params, x):
        from horovod_tpu.compat import jaxshim
        f = jaxshim.shard_map(
            apply, mesh=mesh,
            in_specs=(shard_specs(stacked_params), batch_spec),
            out_specs=batch_spec)
        return f(stacked_params, x)

    return jax.jit(run)
