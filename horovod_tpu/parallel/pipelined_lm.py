"""The flagship TransformerLM with its block tower pipelined.

Composes the GPipe machinery (parallel/pipeline.py) with the real
model: per-block parameters are re-stacked into
``(n_stages, layers_per_stage, ...)``, the stage function scans its
layers locally, microbatches stream between stages over the ``stage``
mesh axis, and the embedding / final-norm / head stay outside the
pipelined region (replicated — they are a sliver of the FLOPs).
Optionally composes with data parallelism over a second axis.

Parameters come from a stock ``TransformerLM.init`` and are
re-assembled with ``from_transformer_params`` — so checkpoints move
freely between the sequential and pipelined forms, and the equivalence
test can demand identical logits.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.transformer import (
    Block, TransformerConfig, TransformerLM,
)
from horovod_tpu.parallel.pipeline import make_pipeline_apply


class PipelinedLM:
    """dp x pp rendering of TransformerLM over a mesh.

    ``cfg.num_layers`` must divide evenly into the stage-axis size, and
    the tower must be homogeneous (``num_experts == 0``: MoE blocks
    alternate structure with dense blocks, which a stage-stacked
    pipeline cannot stack).
    """

    def __init__(self, cfg: TransformerConfig, mesh, *,
                 num_microbatches: int, stage_axis: str = "stage",
                 data_axis: Optional[str] = None):
        if cfg.num_experts != 0:
            raise ValueError(
                "PipelinedLM needs a homogeneous block tower; MoE "
                "configs (num_experts > 0) alternate block structure "
                "and cannot be stage-stacked")
        n_stages = mesh.shape[stage_axis]
        if cfg.num_layers % n_stages != 0:
            raise ValueError(
                f"num_layers={cfg.num_layers} must divide evenly over "
                f"{n_stages} pipeline stages")
        self.cfg = cfg
        self.mesh = mesh
        self.n_stages = n_stages
        self.layers_per_stage = cfg.num_layers // n_stages
        self._block = Block(cfg)
        self._embed = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                               dtype=cfg.dtype, name="embed")
        self._ln_f = nn.LayerNorm(use_bias=False, dtype=cfg.dtype,
                                  param_dtype=jnp.float32, name="ln_f")
        self._head = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=jnp.float32, name="lm_head")

        block = self._block

        def stage_fn(stage_params, h):
            # positions derived per microbatch (batch-size agnostic)
            pos = jnp.broadcast_to(
                jnp.arange(h.shape[1], dtype=jnp.int32)[None],
                h.shape[:2])

            def layer(h, layer_params):
                return block.apply({"params": layer_params}, h, pos), None

            h, _ = lax.scan(layer, h, stage_params)
            return h

        self._run_tower = make_pipeline_apply(
            mesh, stage_fn, num_microbatches=num_microbatches,
            axis=stage_axis, data_axis=data_axis)

    # ------------------------------------------------------------------
    def from_transformer_params(self, variables):
        """Re-stack a stock ``TransformerLM.init`` result into the
        pipelined layout: blocks -> (n_stages, layers_per_stage, ...)."""
        p = variables["params"]
        blocks = [p[f"block_{i}"] for i in range(self.cfg.num_layers)]
        lps = self.layers_per_stage

        def stack(*leaves):
            return jnp.stack(leaves).reshape(
                (self.n_stages, lps) + leaves[0].shape)

        return {
            "embed": p["embed"],
            "blocks": jax.tree_util.tree_map(stack, *blocks),
            "ln_f": p["ln_f"],
            "lm_head": p["lm_head"],
        }

    def init(self, rng, tokens):
        lm = TransformerLM(self.cfg)
        return self.from_transformer_params(
            jax.jit(lm.init)(rng, tokens))

    def apply(self, params, tokens):
        """tokens [B, S] -> logits [B, S, vocab] — same contract (and,
        given re-stacked identical parameters, the same values) as
        ``TransformerLM.apply``."""
        x = self._embed.apply({"params": params["embed"]}, tokens)
        x = self._run_tower(params["blocks"], x)
        x = self._ln_f.apply({"params": params["ln_f"]}, x)
        return self._head.apply({"params": params["lm_head"]},
                                x.astype(jnp.float32))
