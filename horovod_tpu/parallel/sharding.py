"""Rule-based parameter sharding — tensor parallelism without touching
model code.

Not present in the reference (data parallelism only, SURVEY §2.3); the
TPU-native mechanism is GSPMD: annotate parameter shardings, jit the
step under a mesh, and XLA inserts the all-reduces that NCCL-based
frameworks hand-code. Rules are (path-regex → PartitionSpec) pairs
applied to the flattened parameter pytree, the same shape as t5x/maxtext
partitioning rules — the public-domain idiom for this.
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
import numpy as np


class ShardingRules:
    """Ordered (regex, spec) rules; first match wins, default replicated."""

    def __init__(self, rules: Sequence[Tuple[str, "jax.sharding.PartitionSpec"]]):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str, shape=None):
        from jax.sharding import PartitionSpec as P
        for pat, spec in self._rules:
            if pat.search(path):
                return spec
        return P()


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def infer_sharding(params, rules: ShardingRules, mesh):
    """Map a parameter pytree to a pytree of NamedShardings, rejecting
    indivisible placements with an actionable error (e.g. an expert
    axis larger than num_experts) instead of a deep device_put
    failure."""
    from horovod_tpu.compat import jaxshim

    def one(path, leaf):
        p = _path_str(path)
        spec = rules.spec_for(p, getattr(leaf, "shape", None))
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            for dim, axes in zip(shape, tuple(spec)):
                if axes is None:
                    continue
                axes = (axes,) if isinstance(axes, str) else axes
                n = int(np.prod([mesh.shape[a] for a in axes]))
                if n > 1 and dim % n != 0:
                    raise ValueError(
                        f"parameter {p} (shape {tuple(shape)}) cannot "
                        f"shard dim of size {dim} over mesh axes "
                        f"{axes} (total size {n}); pick an axis whose "
                        f"size divides the dimension (for MoE: an "
                        f"expert axis dividing num_experts).")
        return jaxshim.named_sharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params, rules: ShardingRules, mesh):
    """device_put the parameter tree according to the rules."""
    shardings = infer_sharding(params, rules, mesh)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def transformer_tp_rules(model_axis: str = "model",
                         expert_axis: "str | None" = None
                         ) -> ShardingRules:
    """Megatron-style sharding for models/transformer.py: column-split
    the fan-out matmuls (qkv, mlp up), row-split the fan-in matmuls
    (attn out, mlp down) so each block needs one psum on exit; XLA
    inserts it from these annotations.

    With ``expert_axis`` set, MoE expert weights additionally shard
    their leading expert dimension over that axis (expert parallelism —
    GSPMD inserts the token all-to-alls), composed with the Megatron
    split of each expert's hidden dimension over ``model_axis``. The
    fp32 router stays replicated. ``expert_axis`` may name any mesh
    axis, including the data axis (GShard's experts-over-dp layout)."""
    from jax.sharding import PartitionSpec as P
    m = model_axis
    rules = []
    if expert_axis is not None:
        e = expert_axis
        rules += [
            (r"moe/w1$",             P(e, None, m)),
            (r"moe/w2$",             P(e, m, None)),
            (r"moe/router/kernel$",  P()),
        ]
    rules += [
        (r"embed/embedding$",        P(None, m)),
        (r"attn/(q|k|v)/kernel$",    P(None, m, None)),
        (r"attn/o/kernel$",          P(m, None, None)),
        (r"mlp/up/kernel$",          P(None, m)),
        (r"mlp/down/kernel$",        P(m, None)),
        (r"lm_head/kernel$",         P(None, m)),
        # layernorms and everything else: replicated (default)
    ]
    return ShardingRules(rules)


def resnet_dp_rules() -> ShardingRules:
    """ResNet is pure data-parallel: every parameter replicated."""
    return ShardingRules([])


def fsdp_sharding(params, mesh, axis: str = "data",
                  base=None, min_size: int = 1024):
    """FSDP-style (ZeRO-3) parameter sharding via GSPMD: augment each
    parameter's sharding with ``axis`` on its largest still-replicated
    divisible dimension. jit-ing the step with these input shardings
    makes XLA all-gather weights just-in-time for each layer's compute
    and reduce-scatter its gradients — the FSDP schedule. Optimizer
    moments do NOT inherit these shardings automatically (XLA won't
    propagate them through ``zeros_like``): pin ``out_shardings`` when
    jitting ``tx.init``, as ``Trainer.init`` does, so parameter +
    optimizer memory both drop by the axis size. (No reference analog — beyond-parity, like ZeRO-1 in
    horovod_tpu.spmd.zero; this is the GSPMD/pjit rendering where the
    compiler owns the gather/scatter schedule.)

    ``base``: optional pytree of NamedShardings (e.g. from
    :func:`infer_sharding` with tensor-parallel rules) to compose with —
    dims already claimed by other axes are left alone. Leaves smaller
    than ``min_size`` elements (biases, layernorm scales) stay put:
    gathering them costs more than replicating.
    """
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.compat import jaxshim

    n = mesh.shape[axis]

    def one(leaf, base_sh):
        shape = getattr(leaf, "shape", None)
        if shape is None or int(np.prod(shape)) < min_size:
            return base_sh
        spec = list(base_sh.spec)
        spec += [None] * (len(shape) - len(spec))
        used = set()
        for entry in spec:
            if entry is not None:
                used.update((entry,) if isinstance(entry, str) else entry)
        if axis in used:  # e.g. experts already sharded over this axis
            return base_sh
        candidates = [d for d in range(len(shape))
                      if spec[d] is None and shape[d] % n == 0
                      and shape[d] >= n]
        if not candidates:
            return base_sh
        best = max(candidates, key=lambda d: shape[d])
        spec[best] = axis
        return jaxshim.named_sharding(mesh, P(*spec))

    if base is None:
        base = jax.tree_util.tree_map(
            lambda _: jaxshim.named_sharding(mesh, P()), params)
    return jax.tree_util.tree_map(one, params, base)
