"""Rule-based parameter sharding — tensor parallelism without touching
model code.

Not present in the reference (data parallelism only, SURVEY §2.3); the
TPU-native mechanism is GSPMD: annotate parameter shardings, jit the
step under a mesh, and XLA inserts the all-reduces that NCCL-based
frameworks hand-code. Rules are (path-regex → PartitionSpec) pairs
applied to the flattened parameter pytree, the same shape as t5x/maxtext
partitioning rules — the public-domain idiom for this.
"""

from __future__ import annotations

import re
from typing import List, Sequence, Tuple

import jax
import numpy as np


class ShardingRules:
    """Ordered (regex, spec) rules; first match wins, default replicated."""

    def __init__(self, rules: Sequence[Tuple[str, "jax.sharding.PartitionSpec"]]):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str, shape=None):
        from jax.sharding import PartitionSpec as P
        for pat, spec in self._rules:
            if pat.search(path):
                return spec
        return P()


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def infer_sharding(params, rules: ShardingRules, mesh):
    """Map a parameter pytree to a pytree of NamedShardings."""
    from jax.sharding import NamedSharding

    def one(path, leaf):
        spec = rules.spec_for(_path_str(path), getattr(leaf, "shape", None))
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params, rules: ShardingRules, mesh):
    """device_put the parameter tree according to the rules."""
    shardings = infer_sharding(params, rules, mesh)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def transformer_tp_rules(model_axis: str = "model") -> ShardingRules:
    """Megatron-style sharding for models/transformer.py: column-split
    the fan-out matmuls (qkv, mlp up), row-split the fan-in matmuls
    (attn out, mlp down) so each block needs one psum on exit; XLA
    inserts it from these annotations."""
    from jax.sharding import PartitionSpec as P
    m = model_axis
    return ShardingRules([
        (r"embed/embedding$",        P(None, m)),
        (r"attn/(q|k|v)/kernel$",    P(None, m, None)),
        (r"attn/o/kernel$",          P(m, None, None)),
        (r"mlp/up/kernel$",          P(None, m)),
        (r"mlp/down/kernel$",        P(m, None)),
        (r"lm_head/kernel$",         P(None, m)),
        # layernorms and everything else: replicated (default)
    ])


def resnet_dp_rules() -> ShardingRules:
    """ResNet is pure data-parallel: every parameter replicated."""
    return ShardingRules([])
