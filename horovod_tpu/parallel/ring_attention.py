"""Ring attention — sequence/context parallelism over a mesh axis.

Absent from the reference (SURVEY §5: it scales batch, never sequence);
first-class here because long-context is where TPU pods shine. The
sequence is sharded across the ``seq`` mesh axis; each device computes
blockwise attention for its query shard while key/value shards rotate
around the ring via ``jax.lax.ppermute``, accumulating with an online
(flash-style) softmax. Peak memory per device is O(S/p · S/p) for the
logits block instead of O(S²); the p permute steps ride ICI
neighbour-to-neighbour links, the cheapest traffic on a torus.

Causality is positional: block t of the ring carries keys whose global
positions derive from their source shard, so the mask is exact and the
result is bit-for-bit the same math as single-device causal attention
(up to fp32 accumulation order).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from horovod_tpu.compat import jaxshim


def _block_attend(q, k, v, q_pos, k_pos, o, m, l, causal):
    """One blockwise online-softmax update.

    q: [B,Sq,H,D]; k,v: [B,Sk,H,D]; q_pos: [Sq]; k_pos: [Sk]
    o: [B,Sq,H,D] fp32 accumulator; m,l: [B,H,Sq] fp32 running max/sum.
    """
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.float32(d))
    if causal:
        allowed = q_pos[:, None] >= k_pos[None, :]          # [Sq,Sk]
        logits = jnp.where(allowed[None, None], logits, -jnp.inf)
    block_max = jnp.max(logits, axis=-1)                     # [B,H,Sq]
    m_new = jnp.maximum(m, block_max)
    # Fully-masked blocks give m_new == -inf; guard the exp shift.
    shift = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(logits - shift[..., None])                   # [B,H,Sq,Sk]
    if causal:
        p = jnp.where(allowed[None, None], p, 0.0)
    corr = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - shift))
    # First contribution: m == -inf => corr 0 discards the zero state.
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def _ring_einsum(q, k, v, causal: bool, axis: str):
    """Reference ring implementation: jax-level blockwise online
    softmax. Exact; also the differentiation target for the flash
    path's custom VJP."""
    p = jaxshim.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, s_local, h, d = q.shape

    q_pos = idx * s_local + jnp.arange(s_local)

    o = jnp.zeros((b, s_local, h, d), jnp.float32)
    m = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)

    perm = [(i, (i - 1) % p) for i in range(p)]  # shift blocks backwards

    def step(t, carry):
        k_t, v_t, o_t, m_t, l_t = carry
        src = (idx + t) % p                       # owner of current kv
        k_pos = src * s_local + jnp.arange(s_local)
        o_t, m_t, l_t = _block_attend(q, k_t, v_t, q_pos, k_pos,
                                      o_t, m_t, l_t, causal)
        k_n = jax.lax.ppermute(k_t, axis, perm)
        v_n = jax.lax.ppermute(v_t, axis, perm)
        return k_n, v_n, o_t, m_t, l_t

    if p == 1:
        _, _, o, m, l = step(0, (k, v, o, m, l))
    else:
        k_c, v_c, o, m, l = jax.lax.fori_loop(
            0, p, step, (k, v, o, m, l))
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def _ring_flash_fwd_impl(q, k, v, causal: bool, axis: str, block: int):
    """Ring forward where each local block runs the pallas flash
    kernel (flash_attention_stats) and the per-shard (o, m, l) softmax
    statistics are merged across ring steps. kv rotation and merge
    live at the jax level (ppermute on ICI); the O(S_local²) inner
    work never leaves VMEM. Returns (o, m, l) — the merged global
    stats are the backward's residuals."""
    from horovod_tpu.parallel.flash_attention import flash_attention_stats

    p = jaxshim.axis_size(axis)
    b, s_local, h, d = q.shape
    # Only the causal mask reads the positions. Without it the offsets
    # are dead code, and a dead axis_index inside the fori_loop body is
    # hoisted out of the shard_map manual region, where the 0.4.x SPMD
    # partitioner rejects the orphaned partition-id instruction.
    idx = jax.lax.axis_index(axis) if causal else jnp.int32(0)
    q_off = idx * s_local

    o_num = jnp.zeros((b, s_local, h, d), jnp.float32)
    m_run = jnp.full((b, h, s_local), -1e30, jnp.float32)
    l_run = jnp.zeros((b, h, s_local), jnp.float32)

    perm = [(i, (i - 1) % p) for i in range(p)]

    def step(t, carry):
        k_t, v_t, o_num, m_run, l_run = carry
        src = (idx + t) % p
        o_i, m_i, l_i = flash_attention_stats(
            q, k_t, v_t, causal=causal, q_offset=q_off,
            k_offset=src * s_local, block_q=block, block_k=block)
        m_new = jnp.maximum(m_run, m_i)
        a = jnp.exp(m_run - m_new)
        c = jnp.exp(m_i - m_new)
        w = (l_i * c).transpose(0, 2, 1)[..., None]     # [B,S,H,1]
        o_num = o_num * a.transpose(0, 2, 1)[..., None] \
            + o_i.astype(jnp.float32) * w
        l_run = l_run * a + l_i * c
        k_n = jax.lax.ppermute(k_t, axis, perm)
        v_n = jax.lax.ppermute(v_t, axis, perm)
        return k_n, v_n, o_num, m_new, l_run

    if p == 1:
        _, _, o_num, m_run, l_run = step(0, (k, v, o_num, m_run, l_run))
    else:
        _, _, o_num, m_run, l_run = jax.lax.fori_loop(
            0, p, step, (k, v, o_num, m_run, l_run))
    denom = jnp.where(l_run == 0.0, 1.0,
                      l_run).transpose(0, 2, 1)[..., None]
    return (o_num / denom).astype(q.dtype), m_run, l_run


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, causal, axis, block):
    return _ring_flash_fwd_impl(q, k, v, causal, axis, block)[0]


def _ring_flash_fwd(q, k, v, causal, axis, block):
    o, m, l = _ring_flash_fwd_impl(q, k, v, causal, axis, block)
    return o, (q, k, v, o, m, l)


def _ring_flash_bwd(causal, axis, block, residuals, g):
    """Ring backward on the pallas backward kernels: a second kv pass
    where each rotated shard's (dk, dv) accumulators travel with it —
    after p rotations they arrive back at the owning device. Per-shard
    contributions use the globally-merged lse, so their sum is the
    exact full-sequence gradient (same math as the dense backward, up
    to fp32 accumulation order)."""
    from horovod_tpu.parallel.flash_attention import (
        _flash_bwd_bhsd, _lse_from_stats, _to_bhsd, _from_bhsd,
    )

    q, k, v, o, m, l = residuals
    p = jaxshim.axis_size(axis)
    b, s_local, h, d = q.shape
    # See _ring_flash_fwd_impl: keep axis_index out of the trace when
    # the causal mask (its only consumer) is off.
    idx = jax.lax.axis_index(axis) if causal else jnp.int32(0)
    q_off = idx * s_local
    perm = [(i, (i - 1) % p) for i in range(p)]
    interpret = jax.default_backend() not in ("tpu", "axon")

    # Loop-invariant residual prep, done once: layout transposes of the
    # local tensors, lse from the merged stats, delta = rowsum(do·o).
    qb, gb, ob = _to_bhsd(q), _to_bhsd(g), _to_bhsd(o)
    kb, vb = _to_bhsd(k), _to_bhsd(v)
    lse = _lse_from_stats(m, l)
    delta = jnp.sum(gb.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)[:, None, :]   # [BH,1,S], see _lse_from_stats

    dq0 = jnp.zeros(qb.shape, jnp.float32)
    dk0 = jnp.zeros(kb.shape, jnp.float32)
    dv0 = jnp.zeros(vb.shape, jnp.float32)

    def step(t, carry):
        k_t, v_t, dk_t, dv_t, dq = carry
        src = (idx + t) % p
        offsets = jnp.stack([jnp.asarray(q_off, jnp.int32),
                             jnp.asarray(src * s_local, jnp.int32)])
        dq_i, dk_i, dv_i = _flash_bwd_bhsd(
            qb, k_t, v_t, gb, lse, delta, offsets, causal, block,
            block, interpret)
        dq = dq + dq_i.astype(jnp.float32)
        dk_t = dk_t + dk_i.astype(jnp.float32)
        dv_t = dv_t + dv_i.astype(jnp.float32)
        k_n = jax.lax.ppermute(k_t, axis, perm)
        v_n = jax.lax.ppermute(v_t, axis, perm)
        dk_n = jax.lax.ppermute(dk_t, axis, perm)
        dv_n = jax.lax.ppermute(dv_t, axis, perm)
        return k_n, v_n, dk_n, dv_n, dq

    if p == 1:
        _, _, dk, dv, dq = step(0, (kb, vb, dk0, dv0, dq0))
    else:
        _, _, dk, dv, dq = jax.lax.fori_loop(
            0, p, step, (kb, vb, dk0, dv0, dq0))
    return (_from_bhsd(dq, b, h).astype(q.dtype),
            _from_bhsd(dk, b, h).astype(k.dtype),
            _from_bhsd(dv, b, h).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, causal: bool = True, axis: str = "seq",
                   use_flash: Optional[bool] = None):
    """Sequence-parallel causal attention. Call inside ``shard_map``
    with the sequence dimension sharded over ``axis``.

    q, k, v: [B, S_local, H, D] — this device's sequence shard.
    Returns [B, S_local, H, D] in q.dtype.

    ``use_flash`` (default: auto — on TPU with block-divisible local
    sequences) runs each per-shard block through the pallas flash
    kernel and merges softmax statistics across ring steps; gradients
    flow through a second ring over the pallas backward kernels
    against the globally-merged lse.
    """
    s_local = q.shape[1]
    # Same measured tile ladder as flash_attention's defaults: big
    # tiles run the kernels ~4x faster than the old fixed 128
    # (see parallel/flash_attention.py block ladders); shard lengths
    # that divide no ladder entry degrade to the old behavior.
    from horovod_tpu.parallel.flash_attention import (
        _BLOCK_Q_LADDER, _auto_block,
    )
    block = _auto_block(s_local, _BLOCK_Q_LADDER, None)
    if use_flash is None:
        use_flash = (s_local % block == 0
                     and jax.default_backend() in ("tpu", "axon"))
    elif use_flash and s_local % block != 0:
        raise ValueError(
            f"use_flash requires local sequence {s_local} divisible by "
            f"block {block}")
    if use_flash:
        return _ring_flash(q, k, v, bool(causal), axis, block)
    return _ring_einsum(q, k, v, causal, axis)


def _cached_sharded_attention(mesh, spec, inner):
    """Shared wrapper for the sequence-parallel attention factories
    (ring + ulysses): one manual-sharding island per causal value
    (bounded cache of two) so the returned attention_fn honors its
    ``causal`` argument instead of baking one mask in."""
    cache = {}

    def _build(causal: bool):
        @partial(jaxshim.shard_map, mesh=mesh, in_specs=(spec,) * 3,
                 out_specs=spec)
        def _sharded(q, k, v):
            return inner(q, k, v, causal)
        return _sharded

    def attention_fn(q, k, v, causal=True):
        causal = bool(causal)
        if causal not in cache:
            cache[causal] = _build(causal)
        return cache[causal](q, k, v)

    return attention_fn


def make_ring_attention(mesh, data_axis: str = "data",
                        seq_axis: str = "seq",
                        model_axis: Optional[str] = "model"):
    """Build an ``attention_fn`` for TransformerConfig that runs ring
    attention as a manual-sharding island inside an otherwise
    GSPMD-partitioned jit: batch over ``data_axis``, sequence over
    ``seq_axis``, heads over ``model_axis``. Batch and head dimensions
    need no communication; only the kv rotation over ``seq_axis``
    touches the network."""
    from jax.sharding import PartitionSpec as P

    return _cached_sharded_attention(
        mesh, P(data_axis, seq_axis, model_axis, None),
        lambda q, k, v, causal: ring_attention(q, k, v, causal=causal,
                                               axis=seq_axis))
