"""Ring attention — sequence/context parallelism over a mesh axis.

Absent from the reference (SURVEY §5: it scales batch, never sequence);
first-class here because long-context is where TPU pods shine. The
sequence is sharded across the ``seq`` mesh axis; each device computes
blockwise attention for its query shard while key/value shards rotate
around the ring via ``jax.lax.ppermute``, accumulating with an online
(flash-style) softmax. Peak memory per device is O(S/p · S/p) for the
logits block instead of O(S²); the p permute steps ride ICI
neighbour-to-neighbour links, the cheapest traffic on a torus.

Causality is positional: block t of the ring carries keys whose global
positions derive from their source shard, so the mask is exact and the
result is bit-for-bit the same math as single-device causal attention
(up to fp32 accumulation order).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def _block_attend(q, k, v, q_pos, k_pos, o, m, l, causal):
    """One blockwise online-softmax update.

    q: [B,Sq,H,D]; k,v: [B,Sk,H,D]; q_pos: [Sq]; k_pos: [Sk]
    o: [B,Sq,H,D] fp32 accumulator; m,l: [B,H,Sq] fp32 running max/sum.
    """
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.float32(d))
    if causal:
        allowed = q_pos[:, None] >= k_pos[None, :]          # [Sq,Sk]
        logits = jnp.where(allowed[None, None], logits, -jnp.inf)
    block_max = jnp.max(logits, axis=-1)                     # [B,H,Sq]
    m_new = jnp.maximum(m, block_max)
    # Fully-masked blocks give m_new == -inf; guard the exp shift.
    shift = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(logits - shift[..., None])                   # [B,H,Sq,Sk]
    if causal:
        p = jnp.where(allowed[None, None], p, 0.0)
    corr = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - shift))
    # First contribution: m == -inf => corr 0 discards the zero state.
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q, k, v, causal: bool = True,
                   axis: str = "seq"):
    """Sequence-parallel causal attention. Call inside ``shard_map``
    with the sequence dimension sharded over ``axis``.

    q, k, v: [B, S_local, H, D] — this device's sequence shard.
    Returns [B, S_local, H, D] in q.dtype.
    """
    p = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, s_local, h, d = q.shape

    q_pos = idx * s_local + jnp.arange(s_local)

    o = jnp.zeros((b, s_local, h, d), jnp.float32)
    m = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)

    perm = [(i, (i - 1) % p) for i in range(p)]  # shift blocks backwards

    def step(t, carry):
        k_t, v_t, o_t, m_t, l_t = carry
        src = (idx + t) % p                       # owner of current kv
        k_pos = src * s_local + jnp.arange(s_local)
        o_t, m_t, l_t = _block_attend(q, k_t, v_t, q_pos, k_pos,
                                      o_t, m_t, l_t, causal)
        k_n = jax.lax.ppermute(k_t, axis, perm)
        v_n = jax.lax.ppermute(v_t, axis, perm)
        return k_n, v_n, o_t, m_t, l_t

    if p == 1:
        _, _, o, m, l = step(0, (k, v, o, m, l))
    else:
        k_c, v_c, o, m, l = jax.lax.fori_loop(
            0, p, step, (k, v, o, m, l))
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def make_ring_attention(mesh, data_axis: str = "data",
                        seq_axis: str = "seq",
                        model_axis: Optional[str] = "model"):
    """Build an ``attention_fn`` for TransformerConfig that runs ring
    attention as a manual-sharding island inside an otherwise
    GSPMD-partitioned jit: batch over ``data_axis``, sequence over
    ``seq_axis``, heads over ``model_axis``. Batch and head dimensions
    need no communication; only the kv rotation over ``seq_axis``
    touches the network."""
    from jax.sharding import PartitionSpec as P

    spec = P(data_axis, seq_axis, model_axis, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def _sharded(q, k, v):
        return ring_attention(q, k, v, causal=True, axis=seq_axis)

    def attention_fn(q, k, v, causal=True):
        return _sharded(q, k, v)

    return attention_fn
