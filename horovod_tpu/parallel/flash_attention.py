"""Pallas flash attention — the TPU kernel for the attention hot op.

No reference analog (the reference has no model compute at all); this
is the pallas-native realization of blockwise attention so the
flagship Transformer keeps the MXU busy instead of materializing
O(S²) logits in HBM.

Kernel shape (the canonical TPU flash structure):
- 3D grid (batch*heads, q blocks, kv blocks); the kv-block dimension
  is innermost, so each program sees one [BLOCK_Q, D] query tile and
  one [BLOCK_K, D] kv tile in VMEM — kv streams through, nothing
  holds the whole sequence on-chip;
- running max / normalizer / accumulator live in fp32 VMEM scratch,
  initialized at kv step 0 and flushed to HBM at the last kv step;
- softmax statistics are emitted as [BH, S, 1] arrays with
  (1, BLOCK_Q, 1) blocks — both trailing block dims equal the array
  dims, which satisfies the mosaic tiling rule without replicating
  stats across 128 lanes;
- causal block-skip: kv tiles entirely in the future are predicated
  off with `pl.when`, saving ~half the FLOPs of causal attention;
- `offsets` is a runtime int32[2] (scalar-prefetch, SMEM): the global
  positions of q[0] and k[0]. Ring attention passes traced offsets for
  its rotated kv blocks — no retrace per ring step.

Backward is a pair of pallas kernels (the FlashAttention-2 split):
- dq kernel, grid (BH, q blocks, kv blocks): recomputes each p-block
  from (q, k, lse), forms ds = p * (dp - delta) and accumulates
  dq += ds @ k in fp32 scratch;
- dk/dv kernel, grid (BH, kv blocks, q blocks): same recompute per
  tile, accumulates dv += pᵀ @ do and dk += dsᵀ @ q.
delta = rowsum(do · o) is precomputed once outside (one fused XLA
pass, [BH, S, 1]); lse = m + log l comes from the forward's stats, so
no O(S²) buffer exists anywhere in the backward.

``flash_attention``: differentiable via the kernels above.
``flash_attention_stats``: forward-only variant also returning the
(m, l) softmax statistics, which ring attention merges across shards
(horovod_tpu/parallel/ring_attention.py).
``flash_attention_bwd``: the raw backward entry ring attention calls
per rotated kv shard with the globally-merged lse.

Falls back to interpreter mode off-TPU (tests run it on CPU with tiny
shapes) and to the dense implementation when shapes don't meet block
constraints.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
            m_scr, l_scr, acc_scr, *, block_q: int, block_k: int,
            num_k: int, causal: bool, scale: float):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = offs_ref[0] + qi * block_q
    k_start = offs_ref[1] + j * block_k
    # Causal block-skip: the whole kv tile is in the future of the
    # whole q tile -> nothing to do.
    visible = jnp.logical_or(
        jnp.logical_not(causal),
        k_start <= q_start + block_q - 1)

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            allowed = q_pos >= k_pos
            s = jnp.where(allowed, s, _NEG_INF)
        m_prev = m_scr[:]
        block_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(allowed, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_k - 1)
    def _():
        l = l_scr[:]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        # Stats leave as [1, BQ] rows: the HBM stats tensors are
        # [BH, 1, S] so the TPU (8,128) tiling pads the size-1 dim
        # 8x instead of padding a trailing size-1 lane dim 128x.
        m_ref[0] = jnp.transpose(m_scr[:])
        l_ref[0] = jnp.transpose(l)

@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_bhsd(q, k, v, offsets, causal: bool, block_q: int,
                block_k: int, interpret: bool):
    """q: [BH, Sq, D]; k, v: [BH, Sk, D]; offsets: int32[2].
    Returns (o [BH,Sq,D], m [BH,1,Sq], l [BH,1,Sq])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    num_k = seq_k // block_k

    kernel = functools.partial(
        _kernel, block_q=block_q, block_k=block_k, num_k=num_k,
        causal=causal, scale=scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, seq_q // block_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, offs: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, offs: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, offs: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j, offs: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j, offs: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j, offs: (b, 0, i)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
            jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(2 * q.size + k.size + v.size)
            * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
    )(offsets, q, k, v)


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    q_start, k_start, block_q: int, block_k: int,
                    causal: bool, scale: float):
    """Shared backward-tile recompute: p = exp(s - lse) and
    ds = p · (dp − delta) · scale for one [BQ, BK] tile. The dq and
    dk/dv kernels differ only in what they contract these with."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = jnp.transpose(lse_ref[0])                   # [1,BQ] -> [BQ,1]
    delta = jnp.transpose(delta_ref[0])               # [1,BQ] -> [BQ,1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [BQ, BK]
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    # Dead rows (l == 0) store lse = +inf -> p underflows to 0.
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # [BQ, BK]
    ds = p * (dp - delta) * scale
    return q, k, do, p, ds


def _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, block_q: int,
                   block_k: int, num_k: int, causal: bool, scale: float):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = offs_ref[0] + qi * block_q
    k_start = offs_ref[1] + j * block_k
    visible = jnp.logical_or(
        jnp.logical_not(causal),
        k_start <= q_start + block_q - 1)

    @pl.when(visible)
    def _():
        _, k, _, _, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, block_q, block_k, causal, scale)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    block_q: int, block_k: int, num_q: int,
                    causal: bool, scale: float):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)      # kv block (outer)
    qi = pl.program_id(2)     # q block (inner, streams)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = offs_ref[0] + qi * block_q
    k_start = offs_ref[1] + j * block_k
    visible = jnp.logical_or(
        jnp.logical_not(causal),
        k_start <= q_start + block_q - 1)

    @pl.when(visible)
    def _():
        q, _, do, p, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, block_q, block_k, causal, scale)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BK, D]
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BK, D]

    @pl.when(qi == num_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_bwd_bhsd(q, k, v, do, lse, delta, offsets, causal: bool,
                    block_q: int, block_k: int, interpret: bool):
    """Backward kernels. q, do: [BH,Sq,D]; k, v: [BH,Sk,D];
    lse, delta: [BH,1,Sq] fp32. Returns (dq, dk, dv) in input dtypes."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    num_q = seq_q // block_q
    num_k = seq_k // block_k

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j, offs: (b, i, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j, offs: (b, j, 0))
    stat_spec = pl.BlockSpec((1, 1, block_q),
                             lambda b, i, j, offs: (b, 0, i))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=block_q, block_k=block_k,
            num_k=num_k, causal=causal, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, num_q, num_k),
            in_specs=[q_spec, k_spec, k_spec, q_spec, stat_spec,
                      stat_spec],
            out_specs=q_spec,
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(2 * q.size + k.size + v.size)
            * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
    )(offsets, q, k, v, do, lse, delta)

    # dk/dv: swap grid so the kv block is outer and q streams.
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i, offs: (b, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i, offs: (b, j, 0))
    stat_spec2 = pl.BlockSpec((1, 1, block_q),
                              lambda b, j, i, offs: (b, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            num_q=num_q, causal=causal, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, num_k, num_q),
            in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, stat_spec2,
                      stat_spec2],
            out_specs=(k_spec2, k_spec2),
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=10 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(q.size + 2 * (k.size + v.size))
            * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
    )(offsets, q, k, v, do, lse, delta)
    return dq, dk, dv


def _dense_reference(q, k, v, causal: bool, q_offset, k_offset):
    """Mathematically identical dense formulation (fp32 softmax) — the
    shape fallback and the test oracle for the kernels."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.float32(d))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        allowed = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(allowed[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if causal:
        probs = jnp.where(allowed[None, None], probs, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _shapes_ok(seq_q, seq_k, block_q, block_k):
    return seq_q % block_q == 0 and seq_k % block_k == 0


# Default block ladders. Measured on v5e silicon (B=4..8, S=2048,
# D=64..128): 512x1024 tiles run the fwd+bwd kernels 3.8-4.2x faster
# than 128x128 — small tiles pay per-program fixed costs and shallow
# MXU passes far exceeding their VMEM savings. ``None`` block args
# auto-pick the largest ladder entry dividing the sequence, so odd
# lengths (ring shards, tests) degrade gracefully instead of falling
# back to dense.
#
# The 512x1024 default was only ever validated for D <= 128
# (ADVICE r05): the kernels' resident VMEM grows linearly with D —
# per program roughly (block_q + 2*block_k) * D tile elements plus
# the (block_q, D) f32 accumulator (the backward adds do/dq tiles of
# the same shape) — so at D=256 the 512x1024 tile pair already sits
# near ~3 MB of f32 working set and at D=512 it would blow the
# ~16 MB/core VMEM budget outright once double-buffered pipelining
# and the p-block scratch are counted. _ladders_for halves the
# ladder per doubling past 128 so the working set stays roughly
# D-invariant; tiles never drop below the 128-lane MXU width.
_BLOCK_Q_LADDER = (512, 256, 128)
_BLOCK_K_LADDER = (1024, 512, 256, 128)
_HEAD_DIM_BASE = 128  # the largest D the default ladder was measured at


def _ladders_for(head_dim: int):
    """(q_ladder, k_ladder) scaled to ``head_dim``: the measured
    512x1024 defaults up to D=128, then each doubling of D halves the
    leading tiles (floor 128) so per-program VMEM stays level."""
    q_top, k_top = _BLOCK_Q_LADDER[0], _BLOCK_K_LADDER[0]
    d = max(1, int(head_dim))
    while d > _HEAD_DIM_BASE and (q_top > 128 or k_top > 128):
        q_top = max(128, q_top // 2)
        k_top = max(128, k_top // 2)
        d //= 2
    q_ladder = tuple(b for b in _BLOCK_Q_LADDER if b <= q_top)
    k_ladder = tuple(b for b in _BLOCK_K_LADDER if b <= k_top)
    return q_ladder, k_ladder


def _auto_block(seq: int, ladder, explicit) -> int:
    if explicit is not None:
        return min(explicit, seq)
    for b in ladder:
        if seq % b == 0:
            return b
    return min(ladder[-1], seq)


def _to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _run(q, k, v, offsets, causal, block_q, block_k, interpret):
    b, seq_q, h, d = q.shape
    o, m, l = _flash_bhsd(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v), offsets,
                          causal, block_q, block_k, bool(interpret))
    o = _from_bhsd(o, b, h)
    m = m[:, 0].reshape(b, h, seq_q)
    l = l[:, 0].reshape(b, h, seq_q)
    return o, m, l


def flash_attention_stats(q, k, v, causal: bool = True,
                          q_offset=0, k_offset=0,
                          block_q: Optional[int] = None,
                          block_k: Optional[int] = None,
                          interpret: Optional[bool] = None
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Forward-only flash attention that also returns the softmax
    statistics: (o [B,Sq,H,D], m [B,H,Sq] running max, l [B,H,Sq]
    normalizer). Ring attention merges these across rotated kv shards.
    Offsets may be traced values (one compilation serves every ring
    step)."""
    seq_q, seq_k = q.shape[1], k.shape[1]
    q_ladder, k_ladder = _ladders_for(q.shape[-1])
    block_q = _auto_block(seq_q, q_ladder, block_q)
    block_k = _auto_block(seq_k, k_ladder, block_k)
    if not _shapes_ok(seq_q, seq_k, block_q, block_k):
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) must be divisible by "
            f"blocks ({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])
    return _run(q, k, v, offsets, causal, block_q, block_k, interpret)


def _lse_from_stats(m, l):
    """[B,H,S] stats -> [BH,1,S] fp32 lse; +inf marks dead rows so the
    backward's exp(s - lse) underflows to exactly 0 for them. The
    size-1 middle dim keeps S on the 128-lane axis — a trailing size-1
    dim would tile-pad the tensor 128x in HBM."""
    b, h, s = m.shape
    lse = jnp.where(l > 0.0, m + jnp.log(jnp.where(l > 0.0, l, 1.0)),
                    jnp.inf)
    return lse.reshape(b * h, 1, s)


def flash_attention_bwd(q, k, v, o, m, l, do, causal: bool = True,
                        q_offset=0, k_offset=0,
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None,
                        interpret: Optional[bool] = None):
    """Raw flash backward against externally-merged softmax stats.

    q, k, v, o, do: [B,S,H,D]; m, l: [B,H,Sq] (as returned — or ring-
    merged — from flash_attention_stats). Returns (dq, dk, dv) in the
    input dtypes. Ring attention calls this once per rotated kv shard
    with the *global* lse, which makes per-shard contributions sum to
    the exact full-sequence gradient."""
    b, seq_q, h, d = q.shape
    seq_k = k.shape[1]
    q_ladder, k_ladder = _ladders_for(d)
    block_q = _auto_block(seq_q, q_ladder, block_q)
    block_k = _auto_block(seq_k, k_ladder, block_k)
    if not _shapes_ok(seq_q, seq_k, block_q, block_k):
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) must be divisible by "
            f"blocks ({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])
    qb, kb, vb, dob, ob = (_to_bhsd(x) for x in (q, k, v, do, o))
    lse = _lse_from_stats(m, l)
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)[:, None, :]   # [BH,1,S], see _lse_from_stats
    dq, dk, dv = _flash_bwd_bhsd(qb, kb, vb, dob, lse, delta, offsets,
                                 bool(causal), block_q, block_k,
                                 bool(interpret))
    return (_from_bhsd(dq, b, h), _from_bhsd(dk, b, h),
            _from_bhsd(dv, b, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, offsets, causal, block_q, block_k, interpret):
    return _run(q, k, v, offsets, causal, block_q, block_k, interpret)[0]


def _flash_fwd(q, k, v, offsets, causal, block_q, block_k, interpret):
    o, m, l = _run(q, k, v, offsets, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, m, l, offsets)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    import numpy as np
    q, k, v, o, m, l, offsets = residuals
    dq, dk, dv = flash_attention_bwd(
        q, k, v, o, m, l, g, causal=causal,
        q_offset=offsets[0], k_offset=offsets[1],
        block_q=block_q, block_k=block_k, interpret=interpret)
    d_offsets = np.zeros(offsets.shape, jax.dtypes.float0)
    return dq, dk, dv, d_offsets


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    q_offset=0, k_offset=0,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Blockwise-softmax attention. q, k, v: [B, S, H, D] (the module
    layout of models/transformer.py); returns [B, Sq, H, D] in q.dtype.

    ``q_offset``/``k_offset`` (python ints or traced scalars) are the
    global positions of element 0, shifting the causal mask — ring
    attention's rotated kv blocks use this.

    Precision: the in-kernel dots follow jax's matmul-precision config,
    like every other TPU matmul — bf16 multiplies with f32 accumulation
    by default (measured ~1e-2 vs a float64 reference at S=512, i.e.
    BETTER than a dense attention at the same default). Wrap the call
    in ``jax.default_matmul_precision("float32")`` for ~2e-6 agreement
    at several times the MXU cost; the context reaches inside the
    pallas kernel (verified on v5e silicon)."""
    seq_q, seq_k = q.shape[1], k.shape[1]
    q_ladder, k_ladder = _ladders_for(q.shape[-1])
    bq = _auto_block(seq_q, q_ladder, block_q)
    bk = _auto_block(seq_k, k_ladder, block_k)
    if not _shapes_ok(seq_q, seq_k, bq, bk):
        if not causal:
            raise ValueError("non-causal path requires block-divisible "
                             "sequence lengths")
        return _dense_reference(q, k, v, causal, q_offset, k_offset)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])
    return _flash(q, k, v, offsets, bool(causal), bq, bk,
                  bool(interpret))
