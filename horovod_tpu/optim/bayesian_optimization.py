"""Bayesian optimization over the tuning box.

(reference: horovod/common/optim/bayesian_optimization.{h,cc} — GP
surrogate + Expected Improvement acquisition, maximized with L-BFGS
over multiple restarts via third_party/lbfgs.) Here the acquisition is
maximized the same way: a dense random sweep seeds multi-start
L-BFGS-B refinement; when scipy is unavailable the sweep's best
candidate stands alone.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from horovod_tpu.optim.gaussian_process import GaussianProcessRegressor


class BayesianOptimization:
    def __init__(self, bounds: List[Tuple[float, float]],
                 alpha: float = 1e-8, xi: float = 0.01,
                 seed: int = 0):
        """``bounds`` = [(lo, hi)] per dimension
        (reference: bayesian_optimization.h:40-60)."""
        self.bounds = np.asarray(bounds, np.float64)
        self.dim = len(bounds)
        self.xi = xi
        self._gp = GaussianProcessRegressor(alpha=alpha)
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []
        self._rng = np.random.RandomState(seed)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / np.maximum(hi - lo, 1e-12)

    def _denormalize(self, z: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + z * (hi - lo)

    def add_sample(self, x, y: float) -> None:
        """(reference: bayesian_optimization.cc AddSample)"""
        self._xs.append(self._normalize(np.asarray(x, np.float64)))
        self._ys.append(float(y))

    def _expected_improvement(self, z: np.ndarray) -> np.ndarray:
        """(reference: bayesian_optimization.h:85-93 ExpectedImprovement)"""
        mean, std = self._gp.predict(z)
        best = max(self._ys)
        imp = mean - best - self.xi
        zed = np.where(std > 0, imp / std, 0.0)
        # standard normal pdf/cdf without scipy
        pdf = np.exp(-0.5 * zed ** 2) / np.sqrt(2 * np.pi)
        cdf = 0.5 * (1.0 + _erf(zed / np.sqrt(2.0)))
        ei = imp * cdf + std * pdf
        return np.where(std > 0, ei, 0.0)

    def next_sample(self) -> np.ndarray:
        """Fit the GP and return the EI-maximizing point
        (reference: bayesian_optimization.cc NextSample)."""
        if not self._xs:
            return self._denormalize(self._rng.uniform(size=self.dim))
        self._gp.fit(np.stack(self._xs), np.asarray(self._ys))
        cand = self._rng.uniform(size=(2048, self.dim))
        ei = self._expected_improvement(cand)
        best_z = cand[int(np.argmax(ei))]
        best_ei = float(ei[int(np.argmax(ei))])
        refined, refined_ei = self._maximize_ei(cand, ei)
        if refined is not None and refined_ei >= best_ei:
            best_z = refined
        return self._denormalize(best_z)

    def _maximize_ei(self, cand: np.ndarray, ei: np.ndarray,
                     n_starts: int = 5):
        """Multi-start L-BFGS-B refinement of the acquisition maximum
        (reference: bayesian_optimization.cc L-BFGS maximization over
        the GP posterior, third_party/lbfgs). Returns (point in
        normalized coords, its EI), or (None, -inf) without scipy."""
        try:
            from scipy.optimize import minimize
        except ImportError:
            return None, float("-inf")

        def neg_ei(z):
            return -float(self._expected_improvement(
                np.clip(z, 0.0, 1.0)[None, :])[0])

        starts = cand[np.argsort(ei)[-n_starts:]]
        best, best_v = None, float("-inf")
        for s in starts:
            try:
                res = minimize(neg_ei, s, method="L-BFGS-B",
                               bounds=[(0.0, 1.0)] * self.dim)
            except Exception:
                continue
            v = -float(res.fun)
            if np.isfinite(v) and v > best_v:
                best, best_v = np.clip(np.asarray(res.x), 0.0, 1.0), v
        return best, best_v

    def best(self) -> Tuple[Optional[np.ndarray], float]:
        if not self._ys:
            return None, float("-inf")
        i = int(np.argmax(self._ys))
        return self._denormalize(self._xs[i]), self._ys[i]


def _erf(x: np.ndarray) -> np.ndarray:
    """Vectorized erf (Abramowitz & Stegun 7.1.26, |err| < 1.5e-7)."""
    sign = np.sign(x)
    x = np.abs(x)
    a1, a2, a3, a4, a5 = (0.254829592, -0.284496736, 1.421413741,
                          -1.453152027, 1.061405429)
    p = 0.3275911
    t = 1.0 / (1.0 + p * x)
    y = 1.0 - (((((a5 * t + a4) * t) + a3) * t + a2) * t + a1) * t \
        * np.exp(-x * x)
    return sign * y
