"""Gaussian-process regression surrogate for the autotuner.

(reference: horovod/common/optim/gaussian_process.{h,cc} — the reference
uses Eigen + Cholesky; we use numpy, same math: RBF kernel, jittered
Cholesky solve, predictive mean/variance.)
"""

from __future__ import annotations

import numpy as np


class GaussianProcessRegressor:
    """RBF-kernel GP with observation noise alpha
    (reference: gaussian_process.h:30-58)."""

    def __init__(self, alpha: float = 1e-8, length_scale: float = 1.0,
                 signal_variance: float = 1.0):
        self.alpha = alpha
        self.length_scale = length_scale
        self.signal_variance = signal_variance
        self._x = None
        self._y = None
        self._l = None       # cholesky factor
        self._alpha_vec = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        # squared-exponential kernel (reference: gaussian_process.cc Kernel)
        d2 = (np.sum(a ** 2, axis=1)[:, None]
              + np.sum(b ** 2, axis=1)[None, :]
              - 2.0 * a @ b.T)
        return self.signal_variance * np.exp(-0.5 * np.maximum(d2, 0.0)
                                             / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        k = self._kernel(x, x)
        k[np.diag_indices_from(k)] += self.alpha
        # jittered cholesky for numerical safety
        jitter = 0.0
        for _ in range(6):
            try:
                self._l = np.linalg.cholesky(
                    k + jitter * np.eye(len(k)))
                break
            except np.linalg.LinAlgError:
                jitter = max(jitter * 10.0, 1e-10)
        else:
            raise np.linalg.LinAlgError("GP kernel not PD")
        self._x = x
        self._y = y
        z = np.linalg.solve(self._l, y)
        self._alpha_vec = np.linalg.solve(self._l.T, z)

    def predict(self, x: np.ndarray):
        """-> (mean, std) at query points
        (reference: gaussian_process.cc Predict...)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return (np.zeros(len(x)),
                    np.sqrt(self.signal_variance) * np.ones(len(x)))
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha_vec
        v = np.linalg.solve(self._l, ks.T)
        var = (self.signal_variance + self.alpha
               - np.sum(v ** 2, axis=0))
        return mean, np.sqrt(np.maximum(var, 1e-12))
