"""Optimization utilities for the autotuner
(reference: horovod/common/optim/)."""
