"""PyTorch adapter — hook-driven async gradient allreduce.

Re-architecture of the reference's torch binding
(reference: horovod/torch/__init__.py, horovod/torch/mpi_ops.py) for
TPU hosts: torch stays on CPU (the TPU compute path is JAX), gradients
are staged zero-copy through numpy into the background runtime, and the
collective itself rides whichever backend the negotiated response
selects (XLA mesh / socket / local). The async-handle protocol, the
per-parameter hooks that fire as soon as each gradient is accumulated,
``backward_passes_per_step`` accumulation, and the broadcast helpers
keep the reference's exact contract.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu import ops as _ops
from horovod_tpu.ops import (  # noqa: F401
    Average, Sum, poll, synchronize as _synchronize_handle, barrier,
)


def _to_numpy(t):
    """torch CPU tensor -> numpy view (no copy when contiguous)."""
    t = t.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    return t.numpy()


def _from_numpy(arr, like):
    import torch
    out = torch.from_numpy(np.ascontiguousarray(arr))
    return out.to(dtype=like.dtype).reshape(like.shape)


# -- tensor-level ops (reference: horovod/torch/mpi_ops.py) -------------

def _allreduce_impl(tensor, op, name, compression, prescale_factor,
                    postscale_factor):
    comp, ctx = compression.compress(_to_numpy(tensor))
    out = _ops.allreduce(comp, op=op, name=name,
                         prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor)
    return _from_numpy(np.asarray(compression.decompress(out, ctx)), tensor)


@functools.lru_cache(maxsize=None)
def _allreduce_grad_fn():
    """Lazily-built, memoized autograd Function (torch import stays
    optional): the gradient of an allreduce is the allreduce of the
    gradient with the same op semantics (reference: HorovodAllreduce,
    horovod/torch/mpi_ops.py:110-121)."""
    import torch

    class _AllreduceGrad(torch.autograd.Function):
        @staticmethod
        def forward(ctx, tensor, op, name, compression, pre, post):
            # Resolve the auto-name HERE so backward can derive a
            # deterministic grad-op name: backward-node execution
            # order may differ across ranks, so the global noname
            # counter must not be what pairs the gradient
            # collectives.
            if name is None:
                name = _ops._auto_name("allreduce")
            ctx.op, ctx.pre, ctx.post = op, pre, post
            ctx.compression = compression
            ctx.name = name
            return _allreduce_impl(tensor, op, name, compression,
                                   pre, post)

        @staticmethod
        def backward(ctx, grad):
            # Recurse through the PUBLIC allreduce so double
            # backward (create_graph=True) stays differentiable,
            # like the reference's HorovodAllreduce recursion.
            g = allreduce(grad, op=ctx.op, name=f"{ctx.name}.grad",
                          compression=ctx.compression,
                          prescale_factor=ctx.pre,
                          postscale_factor=ctx.post)
            return g, None, None, None, None, None

    return _AllreduceGrad


def allreduce(tensor, op: int = Average, name: Optional[str] = None,
              compression=Compression.none,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Autograd flows through: for a tensor that requires grad, the
    backward pass allreduces the upstream gradient with identical op
    semantics (reference: test_horovod_allreduce_grad,
    test_torch.py:377)."""
    if _wants_grad(tensor):
        return _allreduce_grad_fn().apply(
            tensor, op, name, compression, prescale_factor,
            postscale_factor)
    return _allreduce_impl(tensor, op, name, compression,
                           prescale_factor, postscale_factor)


def allreduce_(tensor, op: int = Average, name: Optional[str] = None):
    """In-place, non-differentiable variant (reference:
    horovod/torch/mpi_ops.py allreduce_). copy_ into a requires_grad
    leaf must run outside autograd."""
    import torch
    with torch.no_grad():
        tensor.copy_(allreduce(tensor, op=op, name=name))
    return tensor


def allreduce_async(tensor, op: int = Average,
                    name: Optional[str] = None) -> int:
    return _ops.allreduce_async(_to_numpy(tensor), op=op, name=name)


@functools.lru_cache(maxsize=None)
def _allgather_grad_fn():
    """Autograd through allgather (reference: HorovodAllgather,
    horovod/torch/mpi_ops.py:236-254): backward is the shared
    ops.allgather_grad — sum-allreduce the upstream gradient, keep
    this rank's dim-0 slice (variable dim-0 supported)."""
    import torch

    class _AllgatherGrad(torch.autograd.Function):
        @staticmethod
        def forward(ctx, tensor, name):
            if name is None:
                name = _ops._auto_name("allgather")
            ctx.name = name
            ctx.d0 = tensor.shape[0] if tensor.dim() else 1
            ctx.in_dtype = tensor.dtype
            out = _ops.allgather(_to_numpy(tensor), name=name)
            return torch.from_numpy(
                np.ascontiguousarray(out)).to(tensor.dtype)

        @staticmethod
        def backward(ctx, grad):
            piece = _ops.allgather_grad(_to_numpy(grad), ctx.d0,
                                        ctx.name)
            return torch.from_numpy(np.ascontiguousarray(piece)).to(
                ctx.in_dtype), None

    return _AllgatherGrad


@functools.lru_cache(maxsize=None)
def _broadcast_grad_fn():
    """Autograd through broadcast (reference: HorovodBroadcast,
    horovod/torch/mpi_ops.py:318-334): backward sum-allreduces the
    upstream gradient on the root, exact zeros elsewhere."""
    import torch

    class _BroadcastGrad(torch.autograd.Function):
        @staticmethod
        def forward(ctx, tensor, root_rank, name):
            if name is None:
                name = _ops._auto_name("broadcast")
            ctx.name = name
            ctx.root_rank = root_rank
            out = _ops.broadcast(_to_numpy(tensor),
                                 root_rank=root_rank, name=name)
            return _from_numpy(np.asarray(out), tensor)

        @staticmethod
        def backward(ctx, grad):
            g = allreduce(grad, op=Sum, name=f"{ctx.name}.grad")
            if rank() != ctx.root_rank:
                g = torch.zeros_like(g)
            return g, None, None

    return _BroadcastGrad


def _wants_grad(tensor):
    import torch
    return torch.is_grad_enabled() and getattr(tensor, "requires_grad",
                                               False)


def allgather(tensor, name: Optional[str] = None):
    if _wants_grad(tensor):
        return _allgather_grad_fn().apply(tensor, name)
    out = _ops.allgather(_to_numpy(tensor), name=name)
    import torch
    return torch.from_numpy(np.ascontiguousarray(out)).to(tensor.dtype)


def allgather_async(tensor, name: Optional[str] = None) -> int:
    return _ops.allgather_async(_to_numpy(tensor), name=name)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    if _wants_grad(tensor):
        return _broadcast_grad_fn().apply(tensor, root_rank, name)
    out = _ops.broadcast(_to_numpy(tensor), root_rank=root_rank, name=name)
    return _from_numpy(np.asarray(out), tensor)


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None):
    """In-place, non-differentiable (reference: broadcast_,
    horovod/torch/mpi_ops.py:383 — the grad-tracked form is
    ``broadcast``). Under no_grad, broadcast takes its plain path and
    copy_ into a requires_grad leaf is legal."""
    import torch
    with torch.no_grad():
        tensor.copy_(broadcast(tensor, root_rank=root_rank, name=name))
    return tensor


def broadcast_async(tensor, root_rank: int = 0,
                    name: Optional[str] = None) -> int:
    return _ops.broadcast_async(_to_numpy(tensor), root_rank=root_rank,
                                name=name)


def alltoall(tensor, name: Optional[str] = None):
    out = _ops.alltoall(_to_numpy(tensor), name=name)
    import torch
    return torch.from_numpy(np.ascontiguousarray(out)).to(tensor.dtype)


def synchronize(handle: int):
    """Wait on an async handle, returning a torch tensor."""
    import torch
    out = _synchronize_handle(handle)
    return torch.from_numpy(np.ascontiguousarray(np.asarray(out)))


# -- DistributedOptimizer (reference: horovod/torch/__init__.py:42-197) --

class _DistributedOptimizer:
    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1, op: int = Average):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [(f"param.{i}.{j}", p)
                     for i, group in enumerate(optimizer.param_groups)
                     for j, p in enumerate(group["params"])]
        # Duplicate-name guard (reference: torch/__init__.py:60-68).
        names = [n for n, _ in named]
        if len(set(names)) != len(names):
            raise ValueError("parameter names must be unique for "
                             "DistributedOptimizer")
        self._param_names = {p: n for n, p in named}
        self._handles = {}          # param -> (handle, ctx)
        self._grad_counts = {}      # param -> backward passes seen
        self._hook_handles = []
        self._register_hooks()

    def _register_hooks(self):
        # post-accumulate-grad hooks: fire the async allreduce the
        # moment each gradient is final, overlapping communication with
        # the rest of the backward pass (reference:
        # torch/__init__.py:95-130 grad-accumulator hooks).
        for group in self._opt.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook(p)))

    def _make_hook(self, p):
        def hook(param):
            self._grad_counts[p] = self._grad_counts.get(p, 0) + 1
            if self._grad_counts[p] == self.backward_passes_per_step:
                self._allreduce_grad(p)
        return hook

    def _allreduce_grad(self, p):
        name = self._param_names.get(p) or f"param.{id(p)}"
        grad = _to_numpy(p.grad)
        if self.backward_passes_per_step > 1:
            grad = grad / self.backward_passes_per_step
        comp, ctx = self._compression.compress(grad)
        handle = _ops.allreduce_async(comp, op=self._op,
                                      name=f"allreduce.{name}")
        self._handles[p] = (handle, ctx)

    def synchronize(self):
        """Drain all in-flight gradient reductions into p.grad
        (reference: torch/__init__.py:132-147)."""
        import torch
        missing = [p for p in self._grad_counts
                   if p not in self._handles
                   and self._grad_counts.get(p, 0) > 0]
        for p in missing:
            # forced sync before enough backward passes (reference:
            # test_force_allreduce pattern): reduce what we have.
            self._allreduce_grad(p)
        for p, (handle, ctx) in list(self._handles.items()):
            out = _synchronize_handle(handle)
            out = self._compression.decompress(np.asarray(out), ctx)
            with torch.no_grad():
                p.grad.copy_(_from_numpy(out, p.grad))
        self._handles.clear()
        self._grad_counts.clear()

    def step(self, closure=None):
        self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *a, **kw):
        if self._handles:
            raise AssertionError(
                "zero_grad called with allreduces in flight; call "
                "optimizer.synchronize() first "
                "(reference: torch/__init__.py zero_grad guard)")
        return self._opt.zero_grad(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._opt, item)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: int = Average):
    """Wrap a torch optimizer: async per-parameter gradient allreduce
    via hooks + synchronize-on-step
    (reference: horovod/torch/__init__.py:160-197)."""
    return _DistributedOptimizer(optimizer, named_parameters, compression,
                                 backward_passes_per_step, op)


# -- state broadcast (reference: horovod/torch/__init__.py:200-348) ------

def broadcast_parameters(params, root_rank: int = 0):
    """params: state_dict or iterable of (name, tensor)."""
    if hasattr(params, "items"):
        items = [(k, v) for k, v in params.items()]
    else:
        items = list(params)
    handles = []
    for name, t in items:
        if t is None or not hasattr(t, "numpy"):
            continue
        handles.append((t, _ops.broadcast_async(
            _to_numpy(t), root_rank=root_rank, name=f"bcast.{name}")))
    import torch
    for t, h in handles:
        out = _synchronize_handle(h)
        with torch.no_grad():
            t.copy_(_from_numpy(np.asarray(out), t))


def broadcast_optimizer_state(optimizer, root_rank: int = 0):
    """Broadcast optimizer.state_dict() tensors and scalars from root
    (reference: horovod/torch/__init__.py:232-348 incl. the
    scalar-wrapping + recursive type restoration).

    In the canonical restore flow only rank ``root_rank`` has state (it
    loaded a checkpoint; workers hold fresh optimizers). Broadcasting
    "whatever exists" would have root submit broadcasts the workers
    never submit and hang the world, so — like the reference
    (horovod/torch/__init__.py:249-271) — ranks with empty state first
    materialize it with a zero-gradient step, and a stateless optimizer
    returns without touching the wire.
    """
    import torch
    # Must not route through DistributedOptimizer.step below (that
    # synchronizes allreduces only this rank submitted), so unwrap to
    # the inner torch optimizer; the LBFGS guard must also see through
    # the wrapper.
    inner = optimizer._opt if isinstance(
        optimizer, _DistributedOptimizer) else optimizer
    if isinstance(inner, torch.optim.LBFGS):
        # Reference parity (horovod/torch/__init__.py:241-245): LBFGS
        # state is deeply nested with None-valued entries; its shape
        # cannot be agreed across ranks by this path-keyed protocol.
        raise ValueError("cannot broadcast torch.optim.LBFGS state")

    state_dict = optimizer.state_dict()

    if not state_dict["state"]:
        # Materialize with zero gradients so every rank ends up with
        # the same state *structure* as a rank that restored from a
        # checkpoint. Frozen params never receive gradients in real
        # training, so the root's restored state has no entries for
        # them — giving them a grad here would make step() create
        # entries only on this rank and desynchronize the broadcast.
        for group in inner.param_groups:
            for p in group["params"]:
                if not p.requires_grad:
                    continue
                if p.grad is None:
                    p.grad = torch.zeros_like(p.data)
                else:
                    with torch.no_grad():
                        p.grad.zero_()
        inner.step()
        state_dict = optimizer.state_dict()

    if not state_dict["state"]:
        # Stateless optimizer (e.g. plain SGD without momentum):
        # nothing to agree on, and every rank takes this exit.
        return

    scalars = {}
    handles = []

    def visit(path, value):
        if isinstance(value, torch.Tensor):
            handles.append((value, _ops.broadcast_async(
                _to_numpy(value), root_rank=root_rank,
                name=f"bcast.os.{path}")))
        elif isinstance(value, (int, float)):
            scalars[path] = value
        elif isinstance(value, dict):
            for k in sorted(value, key=str):
                visit(f"{path}/{k}", value[k])
        elif isinstance(value, (list, tuple)):
            for i, v in enumerate(value):
                visit(f"{path}/{i}", v)

    visit("state", state_dict["state"])
    visit("param_groups", state_dict["param_groups"])

    for t, h in handles:
        out = _synchronize_handle(h)
        with torch.no_grad():
            t.copy_(_from_numpy(np.asarray(out), t))

    # Scalars (lr, momentum, step counters) ride one fused broadcast.
    if scalars:
        keys = sorted(scalars)
        pos = {k: i for i, k in enumerate(keys)}
        vec = np.asarray([float(scalars[k]) for k in keys], np.float64)
        out = np.asarray(_ops.broadcast(vec, root_rank=root_rank,
                                        name="bcast.os.scalars"))

        def converted(path, value):
            # Addressed by path (not a running iterator), so restore
            # order can't drift from visit order. bool is a subclass
            # of int; restore it as bool, not 0.0/1.0.
            broadcasted = out[pos[path]]
            if isinstance(value, bool):
                return bool(broadcasted)
            if isinstance(value, int):
                return int(broadcasted)
            return float(broadcasted)

        def restored(path, value):
            # One dispatch for every container shape. Returns the value
            # to store back: scalars come from the broadcast vector;
            # tuples (e.g. Adam's betas) are immutable so the whole
            # container is rebuilt and reassigned on the parent (the
            # reference's option callbacks likewise assign whole option
            # values); dicts/lists are mutated in place.
            if path in scalars:
                return converted(path, value)
            if isinstance(value, tuple):
                return tuple(restored(f"{path}/{i}", v)
                             for i, v in enumerate(value))
            if isinstance(value, dict):
                for k in sorted(value, key=str):
                    value[k] = restored(f"{path}/{k}", value[k])
            elif isinstance(value, list):
                for i, v in enumerate(value):
                    value[i] = restored(f"{path}/{i}", v)
            return value

        restored("state", state_dict["state"])
        restored("param_groups", state_dict["param_groups"])
        optimizer.load_state_dict(state_dict)


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "is_homogeneous",
    "Average", "Sum", "Compression",
    "allreduce", "allreduce_", "allreduce_async",
    "allgather", "allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "alltoall",
    "poll", "synchronize", "barrier",
    "DistributedOptimizer", "broadcast_parameters",
    "broadcast_optimizer_state",
]
