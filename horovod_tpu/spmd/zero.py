"""ZeRO-1 sharded optimizer — TPU-native extension beyond the reference.

The reference's DistributedOptimizer keeps a full replica of the
optimizer state on every worker and allreduces full gradients
(reference: horovod/tensorflow/__init__.py:151-249,
horovod/torch/__init__.py:95-147). On TPU the profitable data-parallel
refinement is ZeRO stage 1: reduce-scatter each gradient so every mesh
rank reduces only its 1/n shard, run the optimizer update on that shard
(so first/second-moment state is 1/n the size per chip), and all-gather
the parameter updates. Total bytes on the wire equal a ring allreduce
(reduce-scatter + all-gather), but optimizer-state HBM drops by the
data-axis size — the headroom that lets a bigger model or batch fit.

Everything here runs *inside* a shard_map/pjit-traced step with the mesh
axis in scope, like the rest of horovod_tpu.spmd: XLA sees the
reduce-scatter and all-gather as plain collectives it can schedule onto
ICI and overlap with the surrounding compute.

The optimizer state is genuinely device-varying (each rank holds its
own moment shard), so it must cross the shard_map boundary with a
sharded spec — ``P(axis)`` on the moment vectors, ``P()`` on replicated
scalars like Adam's step count. :func:`zero_state_specs` computes that
spec tree; under it, host materialization of the state gathers every
rank's shard (the full flattened moments — checkpointable), and
``device_put`` with the same spec restores each rank's shard exactly.
"""

from __future__ import annotations

from horovod_tpu.spmd import (
    Average, Sum, AxisName, allgather, mesh_rank, mesh_size,
    reducescatter,
)


def _pad_flat(x, n: int):
    """``x`` flattened and zero-padded to a multiple of ``n``."""
    import jax.numpy as jnp

    flat = x.reshape(-1)
    k = -(-flat.size // n)
    if n * k != flat.size:
        flat = jnp.pad(flat, (0, n * k - flat.size))
    return flat, k


def _shard_leaf(x, axis: AxisName):
    """This rank's 1-D shard of ``x``: flatten, zero-pad to a multiple
    of the axis size, take the rank'th contiguous slice."""
    import jax

    flat, k = _pad_flat(x, mesh_size(axis))
    return jax.lax.dynamic_slice_in_dim(flat, mesh_rank(axis) * k, k)


def zero_optimizer(tx, op: int = Average, axis: AxisName = "data"):
    """Wrap an optax GradientTransformation in a ZeRO-1 sharded update.

    Returns an optax-compatible transformation whose ``init`` and
    ``update`` must run inside a shard_map/pjit context with ``axis`` in
    scope (jit a tiny shard_map'd init once to build the state ahead of
    the first step — see docs/zero.md). ``update`` requires ``params``.

    Semantics: gradients are reduce-scattered over ``axis`` (mean for
    ``Average``, the DistributedOptimizer default; plain sum for
    ``Sum``), ``tx`` updates this rank's parameter shard, and the
    resulting update shards are all-gathered so the returned ``updates``
    pytree matches the full parameter shapes — drop-in for
    ``optax.apply_updates``.

    The state is per-rank (each rank's moment shard): pass it through
    shard_map with the specs from :func:`zero_state_specs`, never
    ``P()``.

    Caveat: ``tx`` sees *shards*, so transforms that mix information
    across the whole pytree (e.g. ``optax.clip_by_global_norm``) would
    compute per-rank-different statistics. Use
    :func:`sharded_clip_by_global_norm` inside the chain instead — it
    restores the true global norm with a psum over ``axis``.
    """
    import jax
    import optax

    if op not in (Average, Sum):
        raise ValueError(f"zero_optimizer supports Average/Sum (got {op})")
    # The wrapper advertises ExtraArgs; make the inner tx honor that
    # contract too (plain transformations would TypeError on **extra).
    tx = optax.with_extra_args_support(tx)

    def _grad_shard(g):
        flat, _ = _pad_flat(g, mesh_size(axis))
        return reducescatter(flat, op=op, axis=axis)

    def init_fn(params):
        return tx.init(jax.tree_util.tree_map(
            lambda p: _shard_leaf(p, axis), params))

    def update_fn(grads, state, params=None, **extra):
        if params is None:
            raise ValueError("zero_optimizer.update requires params")
        grad_shards = jax.tree_util.tree_map(_grad_shard, grads)
        param_shards = jax.tree_util.tree_map(
            lambda p: _shard_leaf(p, axis), params)
        upd_shards, new_state = tx.update(grad_shards, state,
                                          param_shards, **extra)

        def _unshard(u, ref):
            full = allgather(u, axis=axis)
            return full[:ref.size].reshape(ref.shape).astype(ref.dtype)

        updates = jax.tree_util.tree_map(_unshard, upd_shards, params)
        return updates, new_state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def zero_state_specs(tx, params, axis_size: int, axis: AxisName = "data"):
    """PartitionSpec tree for the :func:`zero_optimizer` state: the
    spec to use wherever the state crosses a shard_map boundary
    (in_specs/out_specs) or is placed on the mesh (device_put).

    Works host-side, before any state exists: the state *structure* is
    derived with ``jax.eval_shape`` of ``tx.init`` on this rank's shard
    shapes (``ceil(size/axis_size)`` elements per leaf). Moment shards
    are 1-D and get ``P(axis)`` — globally they concatenate into the
    full flattened (padded) moment vector, so host reads see all ranks'
    state and checkpoints round-trip. 0-d leaves (Adam's step count,
    schedule counters) are replicated and get ``P()``.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    def shard_struct(p):
        k = -(-p.size // axis_size)
        return jax.ShapeDtypeStruct((k,), p.dtype)

    abs_state = jax.eval_shape(
        tx.init, jax.tree_util.tree_map(shard_struct, params))
    return jax.tree_util.tree_map(
        lambda leaf: P(axis) if leaf.ndim >= 1 else P(), abs_state)


def sharded_clip_by_global_norm(max_norm: float, axis: AxisName = "data"):
    """``optax.clip_by_global_norm`` for gradient *shards*: each rank
    holds a disjoint 1/n piece of the reduced gradient (the
    :func:`zero_optimizer` inner view), so the true global norm is the
    psum over ``axis`` of per-shard sums of squares. Chain it ahead of
    the inner optimizer: ``zero_optimizer(optax.chain(
    sharded_clip_by_global_norm(1.0), optax.adam(lr)))``."""
    import jax
    import jax.numpy as jnp
    import optax

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None, **extra):
        del params, extra
        leaves = jax.tree_util.tree_leaves(updates)
        local_sq = sum(jnp.sum(jnp.square(u.astype(jnp.float32)))
                       for u in leaves)
        g_norm = jnp.sqrt(jax.lax.psum(local_sq, axis))
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(g_norm, 1e-16))
        clipped = jax.tree_util.tree_map(
            lambda u: (u.astype(jnp.float32) * scale).astype(u.dtype),
            updates)
        return clipped, state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


__all__ = ["zero_optimizer", "zero_state_specs",
           "sharded_clip_by_global_norm"]
