"""In-jit SPMD collectives over a device mesh — the TPU-first data plane.

This is the idiomatic-TPU half of the framework. The reference reaches
its collectives from *outside* the step function: the trainer produces a
gradient, then hands it to a background runtime that negotiates and runs
NCCL/MPI (reference: horovod/common/operations.cc RunLoopOnce +
horovod/common/ops/nccl_operations.cc). On TPU the profitable design is
the inverse: collectives live *inside* the jitted step, where XLA can
fuse them with the surrounding compute, overlap them with the backward
pass, and schedule them onto ICI directly. This module provides that
surface with the same op vocabulary as the host-side API (allreduce /
allgather / broadcast / alltoall / reducescatter — reference:
horovod/torch/mpi_ops.py), as ``jax.lax`` wrappers keyed by mesh axis
names instead of communicator handles.

Hierarchy note: the reference's hierarchical allreduce (local
reduce-scatter → cross-node allreduce → local allgather, reference:
horovod/common/ops/nccl_operations.cc:167-372) is expressed here by
factoring the mesh into ('dcn', 'ici') axes and passing both to
``psum`` — XLA decomposes the reduction per axis, riding ICI
intra-slice and DCN across slices.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple, Union

Average = 0
Sum = 1
Min = 2
Max = 3

AxisName = Union[str, Tuple[str, ...]]


# ---------------------------------------------------------------------------
# Mesh construction
# ---------------------------------------------------------------------------

def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices=None,
                allow_split_physical_axes: bool = False):
    """Build a ``jax.sharding.Mesh``.

    ``axes`` maps axis name → size; at most one size may be ``-1``
    (filled with the remaining devices). Default: one ``'data'`` axis
    over every visible device — the mesh equivalent of the reference's
    MPI_COMM_WORLD (reference: horovod/common/operations.cc:695-727).

    On multi-host platforms the device order comes from
    ``mesh_utils.create_device_mesh`` so that the trailing axes map to
    ICI neighbours and leading axes to DCN, mirroring the reference's
    LOCAL/CROSS communicator split
    (reference: horovod/common/operations.cc:729-764).
    """
    from horovod_tpu.compat import jaxshim
    return jaxshim.make_mesh(
        axes, devices=devices,
        allow_split_physical_axes=allow_split_physical_axes)


def create_hybrid_mesh(ici_axes: Dict[str, int],
                       dcn_axes: Dict[str, int]):
    """Two-level mesh for multi-slice jobs: ``dcn_axes`` shard across
    slices (data-center network), ``ici_axes`` within a slice. The
    TPU-native form of the reference's is_homogeneous + LOCAL/CROSS
    communicator machinery (reference: horovod/common/operations.cc:
    729-764, mpi_context.h GetMPICommunicator)."""
    from horovod_tpu.compat import jaxshim
    return jaxshim.make_hybrid_mesh(ici_axes, dcn_axes)


def mesh_rank(axis: AxisName = "data"):
    """In-jit rank along ``axis`` (reference: horovod_rank,
    horovod/common/operations.cc:1377-1383 — but per-axis)."""
    import jax

    from horovod_tpu.compat import jaxshim
    if isinstance(axis, (tuple, list)):
        import jax.numpy as jnp
        r = jnp.int32(0)
        for a in axis:
            r = r * jaxshim.axis_size(a) + jax.lax.axis_index(a)
        return r
    return jax.lax.axis_index(axis)


def mesh_size(axis: AxisName = "data") -> int:
    from horovod_tpu.compat import jaxshim
    if isinstance(axis, (tuple, list)):
        return math.prod(jaxshim.axis_size(a) for a in axis)
    return jaxshim.axis_size(axis)


# ---------------------------------------------------------------------------
# Collectives (usable inside shard_map/pjit with the named axes in scope)
# ---------------------------------------------------------------------------

def allreduce(x, op: int = Average, axis: AxisName = "data",
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Cross-replica reduction. ``Average`` divides by the axis size —
    the reference's ``average=True`` contract
    (reference: horovod/tensorflow/__init__.py:46-92)."""
    import jax
    import jax.numpy as jnp
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, x.dtype)
    if op == Average:
        y = jax.lax.pmean(x, axis)
    elif op == Sum:
        y = jax.lax.psum(x, axis)
    elif op == Min:
        y = jax.lax.pmin(x, axis)
    elif op == Max:
        y = jax.lax.pmax(x, axis)
    else:
        raise ValueError(f"unknown reduction op {op}")
    if postscale_factor != 1.0:
        y = y * jnp.asarray(postscale_factor, y.dtype)
    return y


def allgather(x, axis: AxisName = "data"):
    """Concatenate each replica's tensor along dim 0
    (reference allgather semantics: variable dim-0 concat,
    horovod/common/ops/mpi_operations.cc:95-173; inside jit all shards
    are necessarily the same shape — variable dim-0 lives on the
    host-side path in horovod_tpu.ops)."""
    import jax
    return jax.lax.all_gather(x, axis, tiled=True)


def broadcast(x, root_rank: int = 0, axis: AxisName = "data"):
    """Every replica receives root's value. Masked-psum formulation —
    branchless, fusable, and correct for any dtype castable to itself
    (reference: horovod/common/ops/mpi_operations.cc:334-358)."""
    import jax
    import jax.numpy as jnp
    idx = mesh_rank(axis)
    contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return jax.lax.psum(contrib, axis)


def alltoall(x, axis: AxisName = "data"):
    """Tiled all-to-all over dim 0: row-block d of the result came from
    replica d. Matches the host-side alltoall block semantics."""
    import jax
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)


def reducescatter(x, op: int = Average, axis: AxisName = "data"):
    """Reduce then keep this replica's dim-0 shard
    (reference: the reduce-scatter stage of NCCLHierarchicalAllreduce,
    horovod/common/ops/nccl_operations.cc:222-236)."""
    from horovod_tpu.compat import jaxshim
    if op not in (Average, Sum):
        raise ValueError("reducescatter supports Average/Sum only "
                         f"(got op={op}); XLA's reduce-scatter is a sum")
    y = jaxshim.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    if op == Average:
        y = y / mesh_size(axis)
    return y


# ---------------------------------------------------------------------------
# Gradient helpers (pytree versions, the DistributedOptimizer hot path)
# ---------------------------------------------------------------------------

def allreduce_gradients(grads, op: int = Average, axis: AxisName = "data",
                        compression=None):
    """Cross-replica (mean) reduction of a gradient pytree. With
    ``compression`` (horovod_tpu.Compression.fp16 / .bf16), gradients
    are cast down before the reduction and restored after — the wire
    compression contract (reference: horovod/tensorflow/compression.py:
    46-64) realized as a cast around psum so XLA fuses it into the
    collective's pack/unpack."""
    import jax

    def one(g):
        if compression is not None:
            g, ctx = compression.compress(g)
            r = allreduce(g, op=op, axis=axis)
            return compression.decompress(r, ctx)
        return allreduce(g, op=op, axis=axis)

    return jax.tree_util.tree_map(one, grads)


def broadcast_variables(tree, root_rank: int = 0, axis: AxisName = "data"):
    """Broadcast a pytree of arrays from ``root_rank`` — in-jit form of
    the reference's broadcast_parameters / BroadcastGlobalVariablesHook
    (reference: horovod/torch/__init__.py:200-229,
    horovod/tensorflow/__init__.py:95-148)."""
    import jax
    return jax.tree_util.tree_map(
        lambda t: broadcast(t, root_rank=root_rank, axis=axis), tree)


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------

def batch_sharding(mesh, axis: AxisName = "data"):
    """NamedSharding that splits dim 0 across ``axis`` (the global-batch
    layout for data parallelism)."""
    from horovod_tpu.compat import jaxshim
    return jaxshim.named_sharding(mesh, jaxshim.partition_spec(axis))

def replicated_sharding(mesh):
    from horovod_tpu.compat import jaxshim
    return jaxshim.named_sharding(mesh, jaxshim.partition_spec())


def shard_batch(mesh, batch, axis: AxisName = "data"):
    """Place a host batch (pytree of arrays) onto the mesh, dim 0 split
    across ``axis``."""
    import jax
    sh = batch_sharding(mesh, axis)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), batch)


# Imported last: zero.py uses the mesh helpers defined above.
from horovod_tpu.spmd.zero import (  # noqa: E402
    zero_optimizer, zero_state_specs, sharded_clip_by_global_norm,
)

__all__ = [
    "Average", "Sum", "Min", "Max",
    "create_mesh", "create_hybrid_mesh", "mesh_rank", "mesh_size",
    "allreduce", "allgather", "broadcast", "alltoall", "reducescatter",
    "allreduce_gradients", "broadcast_variables",
    "batch_sharding", "replicated_sharding", "shard_batch",
    "zero_optimizer", "zero_state_specs", "sharded_clip_by_global_norm",
]
