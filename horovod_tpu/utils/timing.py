"""Steady-state step timing for benchmarks.

One shared implementation of the discipline bench.py and the examples
need on TPU platforms:

- warm up past compilation AND the platform's slow first dispatches
  (remotely-attached chips settle over ~10 calls);
- time in chunks with a real value fetch per chunk — on some platforms
  ``block_until_ready`` can return before execution finishes, so a
  scalar fetch is the only reliable sync point;
- report the median chunk, robust to bursty host/tunnel interference.
"""

from __future__ import annotations

import time
from typing import Callable


def steady_state_sec_per_step(step: Callable[[], object],
                              sync: Callable[[object], None],
                              warmup_steps: int = 10,
                              chunks: int = 4,
                              chunk_steps: int = 5) -> float:
    """Median seconds per ``step()`` call at steady state.

    ``step`` runs one (async-dispatched) training step and returns a
    handle; ``sync`` forces completion of that handle (e.g.
    ``lambda r: float(r[-1])`` fetching the loss). Runs
    ``warmup_steps`` (0 allowed, for cold-start measurements) then
    ``chunks`` timed chunks of ``chunk_steps`` (each clamped to >= 1).
    """
    import numpy as np

    r = None
    for _ in range(max(0, warmup_steps)):
        r = step()
    if r is not None:
        sync(r)
    dts = []
    for _ in range(max(1, chunks)):
        t0 = time.perf_counter()
        for _ in range(max(1, chunk_steps)):
            r = step()
        sync(r)
        dts.append((time.perf_counter() - t0) / max(1, chunk_steps))
    return float(np.median(dts))
