"""Checkpoint / resume.

The reference has no checkpoint subsystem; its supported pattern is
"framework checkpoint on rank 0 + state broadcast at start"
(SURVEY §5; reference: horovod/torch/__init__.py:200-348
broadcast_parameters/broadcast_optimizer_state,
examples/tensorflow_mnist.py rank-0 checkpoint_dir). This module makes
that pattern first-class: rank 0 persists the pytree (orbax when
available, msgpack via flax otherwise), every rank restores through a
broadcast so the world starts bit-identical.
"""

from __future__ import annotations

import hashlib
import os
import re

from horovod_tpu.common import lockdep
from typing import Any, Optional

from horovod_tpu.common import basics
from horovod_tpu.common import logging as hlog

_STEP_RE = re.compile(r"^step_(\d+)$")


def _tree_digest(path: str) -> str:
    """sha256 over a checkpoint's bytes — a flat file directly, an
    orbax directory as sorted (relpath, content) pairs, so the digest
    is stable across both storage backends."""
    h = hashlib.sha256()
    if os.path.isdir(path):
        for root, dirs, files in os.walk(path):
            dirs.sort()
            for name in sorted(files):
                fp = os.path.join(root, name)
                h.update(os.path.relpath(fp, path).encode("utf-8"))
                with open(fp, "rb") as f:
                    for block in iter(lambda: f.read(1 << 20), b""):
                        h.update(block)
    else:
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
    return h.hexdigest()


def verify_checkpoint(path: str) -> bool:
    """True when ``path``'s digest sidecar matches its content (or no
    sidecar exists — pre-digest checkpoints stay restorable). False
    marks a torn or corrupted checkpoint that latest/restore must
    skip."""
    side = f"{path}.digest"
    if not os.path.exists(side):
        return True
    try:
        with open(side, "r", encoding="utf-8") as f:
            want = f.read().strip()
        return bool(want) and _tree_digest(path) == want
    except OSError:
        return False


def _save_tree(path: str, tree: Any) -> None:
    # orbax rejects relative paths; the flax fallback doesn't care —
    # normalize so behavior doesn't depend on which backend is present.
    # Writes are ATOMIC (tmp + rename): a failed or interrupted save
    # must never leave a truncated step_<n> that latest_checkpoint
    # would select over the last complete checkpoint. (_STEP_RE is
    # anchored, so in-progress ``step_<n>.tmp*`` names are invisible
    # to latest/prune.)
    path = os.path.abspath(path)
    tmp = f"{path}.tmp{os.getpid()}"
    import shutil
    try:
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(tmp, tree, force=True)
        except ImportError:
            from flax import serialization
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(serialization.to_bytes(tree))
        # Digest sidecar BEFORE the rename: every step_<n> that
        # becomes visible already has its manifest, so restore can
        # tell a complete checkpoint from external truncation or
        # bit-rot. A kill between sidecar and rename leaves an orphan
        # sidecar — harmless, latest/prune key off step_<n> names.
        digest = _tree_digest(tmp)
        side_tmp = f"{path}.digest.tmp{os.getpid()}"
        with open(side_tmp, "w", encoding="utf-8") as f:
            f.write(digest + "\n")
        os.replace(side_tmp, f"{path}.digest")
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.replace(tmp, path) if os.path.isfile(tmp) \
            else os.rename(tmp, path)
    except BaseException:
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            elif os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        raise


def _load_tree(path: str, target: Optional[Any]) -> Any:
    path = os.path.abspath(path)
    if os.path.isdir(path):
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        return ckptr.restore(path, item=target)
    from flax import serialization
    with open(path, "rb") as f:
        data = f.read()
    if target is None:
        return serialization.msgpack_restore(data)
    return serialization.from_bytes(target, data)


# One background writer so async saves stay ordered (a newer save can
# never be overtaken by an older one still in flight). _pending is
# appended on caller threads and swapped out by the drain; the lock
# keeps an append from racing the swap when saves are issued from more
# than one thread (a future escaping the drain would surface its
# failure only at atexit, after a restore already read around it).
_writer = None
_pending = []
_pending_lock = lockdep.lock("checkpoint._pending_lock")


def _writer_pool():
    global _writer
    with _pending_lock:
        if _writer is None:
            import atexit
            from concurrent.futures import ThreadPoolExecutor
            # Init under the lock: two first-savers racing here would
            # otherwise each build a pool, and two writer threads break
            # the save-ordering guarantee documented above.
            _writer = ThreadPoolExecutor(max_workers=1,
                                         thread_name_prefix="hvd-ckpt")
            # Fire-and-forget saves must not fail silently: surface any
            # write error at interpreter exit even if the caller never
            # drained explicitly.
            atexit.register(_drain_at_exit)
        return _writer


def _drain_at_exit() -> None:
    wait_pending_saves()


def wait_pending_saves() -> None:
    """Block until every async save issued by this process has
    finished (successfully or not), so nothing races a subsequent
    read, prune, or write. Failures are LOGGED here, not raised: the
    Future returned by ``save_checkpoint(block=False)`` is the error
    channel (``fut.result()`` re-raises), and raising a stale,
    possibly already-handled error from an unrelated later save or
    restore would block THAT operation for no reason. Called
    automatically by restore_checkpoint, blocking saves, and at
    interpreter exit."""
    global _pending
    with _pending_lock:
        pending, _pending = _pending, []
    for f in pending:
        try:
            f.result()
        except Exception as e:
            hlog.error(f"async checkpoint save failed: {e!r}")


def _save_impl(directory: str, state: Any, step: int,
               keep: int) -> str:
    path = os.path.join(directory, f"step_{step}")
    _save_tree(path, state)
    steps = sorted(
        (int(m.group(1)) for m in
         (_STEP_RE.match(d) for d in os.listdir(directory)) if m),
        reverse=True)
    for old in steps[keep:]:
        old_path = os.path.join(directory, f"step_{old}")
        try:
            import shutil
            if os.path.isdir(old_path):
                shutil.rmtree(old_path)
            else:
                os.remove(old_path)
            if os.path.exists(f"{old_path}.digest"):
                os.remove(f"{old_path}.digest")
        except OSError as e:
            hlog.warning(f"could not prune checkpoint {old_path}: {e}")
    return path


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3, block: bool = True):
    """Rank 0 writes ``state`` under ``directory/step_<step>``; other
    ranks no-op (reference pattern: checkpoint only on rank 0 —
    examples/keras_imagenet_resnet50.py callbacks gating). Prunes to
    the newest ``keep`` checkpoints.

    ``block=True`` (default) returns the checkpoint path on rank 0.
    ``block=False`` snapshots the tree to host memory immediately —
    so donated/updated device buffers can't corrupt the save — and
    writes on a background thread, returning a
    ``concurrent.futures.Future`` resolving to the path; training
    continues while storage I/O happens (no reference analog — the
    reference blocks on framework savers). Saves are ordered;
    :func:`wait_pending_saves` or the next blocking call drains them.
    """
    if basics.rank() != 0:
        return None
    if not block:
        pool = _writer_pool()  # before the lock: it takes the same one
        snap = _snapshot(state)
        with _pending_lock:
            # submit+append atomically, so a concurrent drain can never
            # observe the future in flight but absent from _pending.
            fut = pool.submit(_save_impl, directory, snap, step, keep)
            _pending.append(fut)
        return fut
    wait_pending_saves()
    return _save_impl(directory, state, step, keep)


def _snapshot(tree):
    """Deep host-numpy copy of the ARRAY leaves of a pytree: the
    caller may mutate or donate the originals the moment
    save_checkpoint(block=False) returns. Non-array leaves (python
    ints, strings, None) pass through untouched so async checkpoints
    serialize with the same leaf types as blocking ones. (jax is a
    hard dependency of both storage backends, so no jax-less fallback
    is needed here.)"""
    import jax
    import numpy as np

    def leaf(a):
        # ndarray / jax array / np scalar expose __array__; python
        # scalars, str, None do not and must keep their type.
        if hasattr(a, "__array__"):
            return np.array(a, copy=True)
        return a

    return jax.tree_util.tree_map(leaf, tree)


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest checkpoint whose digest sidecar verifies — a torn or
    corrupted step_<n> is skipped (with a warning) back to the newest
    complete one instead of poisoning the restore."""
    if not os.path.isdir(directory):
        return None
    steps = sorted(
        (int(m.group(1)) for m in
         (_STEP_RE.match(d) for d in os.listdir(directory)) if m),
        reverse=True)
    for step in steps:
        path = os.path.join(directory, f"step_{step}")
        if verify_checkpoint(path):
            return path
        hlog.warning(f"checkpoint {path} failed its digest check "
                     f"(torn write or corruption); falling back to "
                     f"an older step")
    return None


def restore_checkpoint(directory_or_path: str,
                       target: Optional[Any] = None,
                       broadcast: bool = True) -> Any:
    """Restore the newest checkpoint. With ``broadcast`` (default),
    only rank 0 reads the storage and the tree is broadcast to every
    rank — the reference's resume contract
    (reference: BroadcastGlobalVariablesHook,
    horovod/tensorflow/__init__.py:117-148) — so shared filesystems
    aren't required on workers."""
    # Never read around an in-flight save (failed drained saves are
    # logged; their step file is simply absent, so the newest COMPLETE
    # checkpoint is what restores).
    if basics.rank() == 0:
        wait_pending_saves()
    path = directory_or_path
    if os.path.isdir(path) and latest_checkpoint(path) and \
            not _STEP_RE.match(os.path.basename(path)):
        path = latest_checkpoint(path)
    elif not verify_checkpoint(path):
        # A directly named checkpoint that fails its digest is an
        # explicit error — silently restoring garbage is worse.
        raise ValueError(f"checkpoint {path} failed its digest check "
                         f"(torn write or corruption)")

    if not broadcast or basics.size() <= 1:
        return _load_tree(path, target)

    from horovod_tpu.jax import broadcast_parameters
    if basics.rank() == 0:
        tree = _load_tree(path, target)
    else:
        if target is None:
            raise ValueError(
                "restore_checkpoint(broadcast=True) on non-root ranks "
                "needs ``target`` to know the tree structure")
        tree = target
    return broadcast_parameters(tree, root_rank=0)
