"""Checkpoint / resume.

The reference has no checkpoint subsystem; its supported pattern is
"framework checkpoint on rank 0 + state broadcast at start"
(SURVEY §5; reference: horovod/torch/__init__.py:200-348
broadcast_parameters/broadcast_optimizer_state,
examples/tensorflow_mnist.py rank-0 checkpoint_dir). This module makes
that pattern first-class: rank 0 persists the pytree (orbax when
available, msgpack via flax otherwise), every rank restores through a
broadcast so the world starts bit-identical.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

from horovod_tpu.common import basics
from horovod_tpu.common import logging as hlog

_STEP_RE = re.compile(r"^step_(\d+)$")


def _save_tree(path: str, tree: Any) -> None:
    # orbax rejects relative paths; the flax fallback doesn't care —
    # normalize so behavior doesn't depend on which backend is present.
    path = os.path.abspath(path)
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, tree, force=True)
        return
    except ImportError:
        pass
    from flax import serialization
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(tree))


def _load_tree(path: str, target: Optional[Any]) -> Any:
    path = os.path.abspath(path)
    if os.path.isdir(path):
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        return ckptr.restore(path, item=target)
    from flax import serialization
    with open(path, "rb") as f:
        data = f.read()
    if target is None:
        return serialization.msgpack_restore(data)
    return serialization.from_bytes(target, data)


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> Optional[str]:
    """Rank 0 writes ``state`` under ``directory/step_<step>``; other
    ranks no-op (reference pattern: checkpoint only on rank 0 —
    examples/keras_imagenet_resnet50.py callbacks gating). Returns the
    checkpoint path on rank 0, None elsewhere. Prunes to the newest
    ``keep`` checkpoints."""
    if basics.rank() != 0:
        return None
    path = os.path.join(directory, f"step_{step}")
    _save_tree(path, state)
    steps = sorted(
        (int(m.group(1)) for m in
         (_STEP_RE.match(d) for d in os.listdir(directory)) if m),
        reverse=True)
    for old in steps[keep:]:
        old_path = os.path.join(directory, f"step_{old}")
        try:
            import shutil
            if os.path.isdir(old_path):
                shutil.rmtree(old_path)
            else:
                os.remove(old_path)
        except OSError as e:
            hlog.warning(f"could not prune checkpoint {old_path}: {e}")
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    steps = sorted(
        (int(m.group(1)) for m in
         (_STEP_RE.match(d) for d in os.listdir(directory)) if m))
    if not steps:
        return None
    return os.path.join(directory, f"step_{steps[-1]}")


def restore_checkpoint(directory_or_path: str,
                       target: Optional[Any] = None,
                       broadcast: bool = True) -> Any:
    """Restore the newest checkpoint. With ``broadcast`` (default),
    only rank 0 reads the storage and the tree is broadcast to every
    rank — the reference's resume contract
    (reference: BroadcastGlobalVariablesHook,
    horovod/tensorflow/__init__.py:117-148) — so shared filesystems
    aren't required on workers."""
    path = directory_or_path
    if os.path.isdir(path) and latest_checkpoint(path) and \
            not _STEP_RE.match(os.path.basename(path)):
        path = latest_checkpoint(path)

    if not broadcast or basics.size() <= 1:
        return _load_tree(path, target)

    from horovod_tpu.jax import broadcast_parameters
    if basics.rank() == 0:
        tree = _load_tree(path, target)
    else:
        if target is None:
            raise ValueError(
                "restore_checkpoint(broadcast=True) on non-root ranks "
                "needs ``target`` to know the tree structure")
        tree = target
    return broadcast_parameters(tree, root_rank=0)
