"""Utilities: checkpoint/resume, seeded data sharding."""

from horovod_tpu.utils.checkpoint import (
    save_checkpoint, restore_checkpoint, latest_checkpoint,
)

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_checkpoint"]
