"""Utilities: checkpoint/resume, seeded data sharding."""

from horovod_tpu.utils.checkpoint import (
    save_checkpoint, restore_checkpoint, latest_checkpoint,
    wait_pending_saves,
)

__all__ = ["save_checkpoint", "restore_checkpoint",
           "latest_checkpoint", "wait_pending_saves"]
