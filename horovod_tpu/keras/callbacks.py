"""Keras callbacks (reference: horovod/_keras/callbacks.py:1-168,
re-exported by horovod/keras/callbacks.py and
horovod/tensorflow/keras/callbacks.py)."""

from __future__ import annotations

from typing import Optional

import numpy as np
import keras

from horovod_tpu import ops as _ops
from horovod_tpu.common import basics
from horovod_tpu.ops import Average


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast initial weights from root at train start
    (reference: _keras/callbacks.py:20-30)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_begin(self, batch, logs=None):
        if self.broadcast_done:
            return
        from horovod_tpu.keras import broadcast_global_variables
        broadcast_global_variables(self.model, self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over ranks before other callbacks
    (checkpointers, early stopping) read them
    (reference: _keras/callbacks.py:33-67)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for key in sorted(logs):
                try:
                    v = np.asarray(float(logs[key]), np.float64)
                except (TypeError, ValueError):
                    continue
                logs[key] = float(np.asarray(_ops.allreduce(
                    v, op=Average, name=f"metric.{epoch}.{key}")))


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """lr = initial_lr * multiplier(epoch) over [start_epoch, end_epoch)
    (reference: _keras/callbacks.py:70-117)."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 initial_lr: Optional[float] = None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = initial_lr
        self.current_epoch = 0
        self.restore_momentum = None
        if not callable(multiplier):
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _autodetect_initial_lr(self):
        if self.initial_lr is None:
            lr = self.model.optimizer.learning_rate
            self.initial_lr = float(np.asarray(lr))

    def _in_range(self, epoch):
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _adjust(self, epoch):
        if not self._in_range(epoch):
            return
        self._autodetect_initial_lr()
        new_lr = self.initial_lr * self.multiplier(epoch)
        # Momentum correction: scale momentum-carried velocity when lr
        # jumps (reference: _keras/callbacks.py:108-117 restore/adjust).
        opt = self.model.optimizer
        if self.momentum_correction and hasattr(opt, "momentum"):
            old_lr = float(np.asarray(opt.learning_rate))
            if old_lr > 0 and new_lr != old_lr:
                mom = float(np.asarray(opt.momentum))
                self.restore_momentum = mom
                opt.momentum = mom * new_lr / old_lr
        self.model.optimizer.learning_rate = new_lr

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._adjust(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self.params.get("steps"):
            frac = batch / float(self.params["steps"])
            self._adjust(self.current_epoch + frac)

    def on_epoch_end(self, epoch, logs=None):
        if self.restore_momentum is not None:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None
        if logs is not None and getattr(self.model, "optimizer", None) \
                is not None:
            logs["lr"] = float(np.asarray(
                self.model.optimizer.learning_rate))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr to lr*size over warmup_epochs
    (Goyal et al.; reference: _keras/callbacks.py:120-168)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0,
                 initial_lr: Optional[float] = None):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            # epoch may be fractional (per-batch); ramp 1 → size
            n = max(basics.size(), 1)
            progress = min(max(epoch / float(warmup_epochs), 0.0), 1.0)
            return 1.0 + progress * (n - 1.0)

        super().__init__(multiplier=multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         initial_lr=initial_lr)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.warmup_epochs - 1 and self.verbose and \
                basics.rank() == 0:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to "
                  f"{np.asarray(self.model.optimizer.learning_rate)}.")
