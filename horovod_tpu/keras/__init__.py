"""Keras adapter (Keras 3, any backend — JAX recommended on TPU).

Role-equivalent of the reference's Keras facades
(reference: horovod/keras/__init__.py:1-148,
horovod/tensorflow/keras/__init__.py, shared impl horovod/_keras/):
``DistributedOptimizer`` averaging gradients across workers,
``broadcast_global_variables``, ``load_model``, and the callback suite
in ``horovod_tpu.keras.callbacks``. Tensors are staged through numpy,
so the adapter is backend-agnostic; the collective itself runs on
whichever backend the negotiated response selects.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, initialized, rank, size, local_rank, local_size,
)
from horovod_tpu.common.compression import Compression  # noqa: F401
from horovod_tpu import ops as _ops
from horovod_tpu.ops import Average, Sum  # noqa: F401

from horovod_tpu.keras import callbacks  # noqa: F401


def _distributed_class(cls, compression, op: int,
                       sparse_as_dense: bool = False):
    """Subclass of optimizer class ``cls`` whose ``apply_gradients``
    first averages gradients across workers (reference:
    _keras/__init__.py:20-70 create_distributed_optimizer, which
    overrides get_gradients; Keras 3's seam is apply_gradients)."""
    import keras

    def _host_allreduce(host: np.ndarray, idx: int) -> np.ndarray:
        comp, ctx = compression.compress(host)
        out = _ops.allreduce(comp, op=op, name=f"keras.grad.{idx}")
        return np.asarray(compression.decompress(np.asarray(out), ctx),
                          dtype=host.dtype)

    def _reduce_sparse(g, idx: int, tf):
        """IndexedSlices (embedding gradients) take the allgather path
        like the reference (reference:
        horovod/tensorflow/__init__.py:72-83): gather every rank's
        (values, indices); averaging divides values by size — repeated
        indices sum on scatter, which IS the correct average of the
        dense equivalent. Works traced or eager (py_function executes
        immediately under eager)."""
        def _host(v, i):
            vals = np.asarray(_ops.allgather(
                v.numpy(), name=f"keras.grad.{idx}.values"))
            inds = np.asarray(_ops.allgather(
                i.numpy(), name=f"keras.grad.{idx}.indices"))
            if op == Average:
                vals = (vals / size()).astype(vals.dtype)
            return vals, inds

        vals, inds = tf.py_function(
            _host, [g.values, g.indices],
            Tout=(g.values.dtype, g.indices.dtype))
        vals.set_shape([None] + list(g.values.shape[1:]))
        inds.set_shape([None])
        return tf.IndexedSlices(vals, inds,
                                dense_shape=g.dense_shape)

    def _reduce_tensor(g, idx: int):
        """Average one gradient. ``model.fit`` traces apply_gradients
        inside the backend's jit (tf.function / jax.jit), so the host
        round-trip must be a callback op, not an eager conversion —
        every rank's compiled step hits the callback at the same
        point, preserving negotiation order."""
        backend = keras.backend.backend()
        if backend == "tensorflow":
            import tensorflow as tf
            if isinstance(g, tf.IndexedSlices):
                if sparse_as_dense:
                    # densify (scatter-add) and ride the dense reduce —
                    # wins when the embedding is small enough that one
                    # psum beats gathering all ranks' slices
                    # (reference: tensorflow/__init__.py:157,195-202)
                    g = tf.convert_to_tensor(g)
                else:
                    return _reduce_sparse(g, idx, tf)
            if not tf.executing_eagerly():
                out = tf.py_function(
                    lambda t: _host_allreduce(t.numpy(), idx), [g],
                    Tout=g.dtype)
                out.set_shape(g.shape)
                return out
        elif backend == "jax":
            import jax
            if isinstance(g, jax.core.Tracer):
                # io_callback(ordered=True): the collective is a
                # blocking side effect; pure_callback could be
                # reordered/deduped/elided by XLA, desynchronizing the
                # ranks' submission order.
                from jax.experimental import io_callback
                return io_callback(
                    lambda t: _host_allreduce(np.asarray(t), idx),
                    jax.ShapeDtypeStruct(g.shape, g.dtype), g,
                    ordered=True)
        host = np.asarray(keras.ops.convert_to_numpy(g))
        return keras.ops.convert_to_tensor(_host_allreduce(host, idx))

    class _Distributed(cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            reduced = [
                None if g is None else _reduce_tensor(g, i)
                for i, (g, _) in enumerate(grads_and_vars)]
            variables = [v for _, v in grads_and_vars]
            return super().apply_gradients(
                zip(reduced, variables), *args, **kwargs)

    # Keep the base NAME so configs record e.g. "SGD", but leave the
    # module as horovod_tpu.keras ON PURPOSE: a saved distributed model
    # restored by a plain keras load fails loudly ("Could not locate
    # class") instead of silently coming back undistributed — the same
    # failure mode as the reference, whose hvd.load_model supplies the
    # custom_objects mapping (reference: _keras/__init__.py:93-109).
    _Distributed.__name__ = cls.__name__
    _Distributed.__qualname__ = cls.__qualname__
    return _Distributed


def DistributedOptimizer(optimizer, compression=Compression.none,
                         op: int = Average, name: Optional[str] = None,
                         sparse_as_dense: bool = False):
    """Wrap a live Keras-3 optimizer instance; see _distributed_class.

    The instance is re-classed rather than rebuilt from config: a
    from_config round-trip would silently drop accumulated slot
    variables / iteration count on load_model-restored optimizers."""
    optimizer.__class__ = _distributed_class(
        optimizer.__class__, compression, op, sparse_as_dense)
    return optimizer


def broadcast_global_variables(model, root_rank: int = 0) -> None:
    """Broadcast model (+ optimizer) weights from root
    (reference: horovod/keras/__init__.py broadcast_global_variables).
    The reference took only (root_rank) and read the TF1 session's
    global variables; Keras 3 has no such session, so the model must
    be passed — calls in the old shape fail with guidance instead of
    binding the rank to ``model``."""
    if isinstance(model, int):
        raise TypeError(
            "broadcast_global_variables(root_rank) needs the model in "
            "Keras 3: call broadcast_global_variables(model, "
            "root_rank=...) or use callbacks."
            "BroadcastGlobalVariablesCallback(root_rank).")
    weights = model.get_weights()
    new_weights = []
    for i, w in enumerate(weights):
        out = _ops.broadcast(np.asarray(w), root_rank=root_rank,
                             name=f"keras.bcast.{i}")
        new_weights.append(np.asarray(out).astype(w.dtype))
    model.set_weights(new_weights)
    opt = getattr(model, "optimizer", None)
    if opt is not None and getattr(opt, "variables", None):
        for j, var in enumerate(opt.variables):
            host = np.asarray(var)
            out = _ops.broadcast(host, root_rank=root_rank,
                                 name=f"keras.bcast.opt.{j}")
            var.assign(np.asarray(out).astype(host.dtype)
                       .reshape(host.shape))


def load_model(filepath, custom_objects=None, compression=Compression.none):
    """Load a Keras model, resolving distributed optimizers saved under
    their base names and wrapping plain ones (reference:
    _keras/__init__.py:93-109 load_model + custom_objects factory)."""
    import keras

    cos = dict(custom_objects or {})
    for attr in dir(keras.optimizers):
        c = getattr(keras.optimizers, attr)
        if (isinstance(c, type)
                and issubclass(c, keras.optimizers.Optimizer)
                and c is not keras.optimizers.Optimizer):
            cos.setdefault(attr,
                           _distributed_class(c, compression, Average))
    model = keras.models.load_model(filepath, custom_objects=cos)
    if getattr(model, "optimizer", None) is not None and \
            not getattr(model.optimizer, "_hvd_wrapped", False):
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model


__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "Average", "Sum", "Compression", "callbacks",
    "DistributedOptimizer", "broadcast_global_variables", "load_model",
]
