"""Self-operation: supervision policy, fast rejoin sync, async checkpoints.

Closes the loop between the telemetry the fleet already publishes and the
actuators the elastic/launcher layers already expose:

  * **Supervision policy** -- a rank-0 ``SupervisionPolicy`` watches preemption
    notices (SIGTERM-with-grace or a ``HOROVOD_PREEMPT_NOTICE`` file) and the
    straggler attribution window, and decides: drain-and-resize proactively on
    a preemption instead of waiting for the hard kill, or demote a habitual
    last-arriver to the ring tail.  Verdicts are world-replicated descriptors
    (``SupervisionVerdict``) installed through ``@world_coherent`` paths so the
    hvdlint coherence analyzer covers them.

  * **Rejoin sync** -- ``sync_state`` replaces ``State.sync``'s naive per-key
    broadcast with a chunked, optionally wire-dtype-compressed, zero-copy
    stream over an ephemeral host-grouped tree that rides the native
    cut-through relay (``hvd_relay_frame``) on interior nodes.

  * **Async checkpoints** -- each rank persists its shard of the committed
    ``State`` during idle/hold windows, with atomic-rename + digest-manifest
    commit, so a below-min-world death restarts from seconds ago via the
    launcher restart path.

Everything here is process-lifetime machinery that must survive elastic
re-initialisation, so knobs are read through ``hconfig.env_*`` at use sites
(the flight-recorder precedent) rather than being ``Config`` fields.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import config as hconfig
from . import lockdep
from . import logging as hlog
from . import network
from . import wire
from .invariants import world_coherent

# Tag used on the ephemeral rejoin-sync tree (distinct from the elastic
# rendezvous RDZV_TAG=1 so a stray frame is an instant protocol error).
SYNC_TAG = 2

_SHARD_RE = re.compile(r"^shard_s(\d+)_r(\d+)_of_(\d+)\.json$")


def _enabled() -> bool:
    return hconfig.env_bool("HOROVOD_SELFOP", True)


# ---------------------------------------------------------------------------
# World-replicated supervision verdict
# ---------------------------------------------------------------------------


class SupervisionVerdict:
    """Last supervision decision, replicated on every rank at install time.

    The coordinator folds the pending policy decision into the elastic
    rendezvous verdict frames, so every member of a new generation installs
    the identical descriptor in the same resize that enacts it.  A resize
    with no pending decision installs an empty verdict (kind ``""``), i.e.
    pacing does not silently persist across unrelated resizes.
    """

    def __init__(self) -> None:
        self.kind = ""  # hvdlint: world-replicated
        self.target_rank = -1  # hvdlint: world-replicated
        self.generation = -1  # hvdlint: world-replicated
        self.cause = ""  # hvdlint: world-replicated
        self.pace_us = 0  # hvdlint: world-replicated

    @world_coherent
    def install(self, kind: str, target_rank: int, generation: int,
                cause: str, pace_us: int) -> None:
        self.kind = kind
        self.target_rank = int(target_rank)
        self.generation = int(generation)
        self.cause = cause
        self.pace_us = int(pace_us)
        if kind:
            from . import trace as htrace
            htrace.flight().record(
                wire.EV_SELFOP, arg=generation,
                note=f"verdict kind={kind} target={target_rank} "
                     f"gen={generation} pace_us={pace_us} cause={cause}")

    def line(self) -> str:
        if not self.kind:
            return ""
        return (f"selfop: {self.kind} target={self.target_rank} "
                f"gen={self.generation} pace_us={self.pace_us} cause={self.cause}")


_verdict = SupervisionVerdict()


def verdict() -> SupervisionVerdict:
    return _verdict


# ---------------------------------------------------------------------------
# Preemption notice (SIGTERM-with-grace or notice file)
# ---------------------------------------------------------------------------

_preempt = threading.Event()
_grace_timer: Optional[threading.Timer] = None
_wake_cb: Optional[Callable[[], None]] = None
_prev_sigterm = None
_handler_installed = False


def preempted() -> bool:
    return _preempt.is_set()


def notice_preemption() -> None:
    """Mark this process preempted (testing / notice-endpoint hook)."""
    _arm_preemption()


def _grace_seconds() -> float:
    return hconfig.env_float("HOROVOD_PREEMPT_GRACE", 30.0)


def _arm_preemption() -> None:
    global _grace_timer
    if _preempt.is_set():
        return
    _preempt.set()
    t = threading.Timer(_grace_seconds(), os._exit, args=(0,))
    t.daemon = True
    t.start()
    _grace_timer = t
    cb = _wake_cb
    if cb is not None:
        try:
            cb()
        except Exception:
            pass


def _on_sigterm(signum, frame):  # signal context: no logging, no locks
    _arm_preemption()


def install_signal_handler(wake_cb: Optional[Callable[[], None]] = None) -> bool:
    """Install the SIGTERM grace handler (main thread only; idempotent)."""
    global _wake_cb, _prev_sigterm, _handler_installed
    if wake_cb is not None:
        _wake_cb = wake_cb
    if _handler_installed:
        return True
    if not _enabled():
        return False
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread
        return False
    _handler_installed = True
    return True


def _notice_file_hit(launch_rank: int) -> bool:
    path = hconfig.env_str("HOROVOD_PREEMPT_NOTICE", "")
    if not path or not os.path.exists(path):
        return False
    try:
        with open(path, "r", encoding="utf-8") as fh:
            body = fh.read().strip()
    except OSError:
        return False
    if not body:
        return True  # empty notice preempts every rank on this host
    for tok in body.replace(",", " ").split():
        try:
            if int(tok) == launch_rank:
                return True
        except ValueError:
            continue
    return False


def retire_if_preempted() -> None:
    """If this process was preempted, shut down cleanly and exit 0.

    Called from the elastic recovery path: the launcher counts a zero exit as
    a clean retirement and never respawns the slot, so the preempted host
    leaves the fleet without a blacklist entry.
    """
    if not _preempt.is_set():
        return
    hlog.info("selfop: preempted, retiring cleanly after drain")
    try:
        from . import basics
        basics.shutdown()
    except Exception:
        pass
    os._exit(0)


# ---------------------------------------------------------------------------
# Supervision policy (rank-0 decision loop)
# ---------------------------------------------------------------------------


class SupervisionPolicy:
    """Consumes live telemetry and produces resize/demote verdicts.

    Process-lifetime: survives elastic re-initialisation so decision counters
    and the demotion memory persist across generations.
    """

    def __init__(self, rank: int) -> None:
        self.rank = rank
        self.launch_rank = hconfig.env_int("HOROVOD_RANK", rank)
        self.decisions: Dict[str, int] = {}
        self._demoted: set = set()
        self._pending_demote: Optional[Tuple[int, int]] = None
        self._last_gen = -1
        self._last_gen_change = 0.0
        self._last_line = ""

    def _count(self, kind: str) -> None:
        self.decisions[kind] = self.decisions.get(kind, 0) + 1

    def tick(self, runtime=None) -> Optional[Tuple[str, int]]:
        """One supervision step.  Returns ``(cause, origin_rank)`` when the
        policy wants a drain-and-resize, else None.

        Preemption checks run on *every* rank (the preempted process is the
        one that knows); demotion analysis is coordinator-only.
        """
        if not _enabled():
            return None
        if _preempt.is_set() or _notice_file_hit(self.launch_rank):
            if not _preempt.is_set():
                _arm_preemption()
            self._count("preempt_drain")
            self._last_line = f"preempt_drain origin={self.rank}"
            return ("preempt", self.rank)
        if self.rank == 0 and runtime is not None:
            return self._maybe_demote(runtime)
        return None

    # -- demotion ----------------------------------------------------------

    def _maybe_demote(self, runtime) -> Optional[Tuple[str, int]]:
        tracker = getattr(runtime, "_straggler", None)
        if tracker is None or self._pending_demote is not None:
            return None
        try:
            from . import elastic as helastic
            ctx = helastic.context()
        except Exception:
            return None
        if ctx is None:
            return None
        gen = ctx.membership.generation
        now = time.monotonic()
        if gen != self._last_gen:
            self._last_gen = gen
            self._last_gen_change = now
        if now - self._last_gen_change < 5.0:  # churn cooldown
            return None
        stats = tracker.window_stats()
        window = stats["window"]
        if window < hconfig.env_int("HOROVOD_SELFOP_DEMOTE_WINDOW", 200):
            return None
        counts = stats["last_counts"]
        if not counts:
            return None
        worst = max(counts, key=lambda r: counts[r])
        share = counts[worst] / float(window)
        if share < hconfig.env_float("HOROVOD_SELFOP_DEMOTE_PCT", 0.6):
            return None
        if worst in (0, self.rank) or worst in self._demoted:
            return None
        lag = stats["max_lag"].get(worst, 0.0)
        if lag <= 0.0:
            return None
        controller = getattr(runtime, "controller", None)
        if controller is not None:
            ages = getattr(controller, "peer_heartbeat_ages", None)
            if callable(ages):
                try:
                    age = ages().get(worst, 0.0)
                    to = getattr(runtime.config, "heartbeat_timeout_s",
                                 0.0) or 0.0
                    if to and age > to / 2.0:
                        return None  # peer may be dying, not slow: let liveness decide
                except Exception:
                    pass
        pace_max = hconfig.env_float("HOROVOD_SELFOP_PACE_MAX_MS", 50.0) / 1e3
        pace_us = int(min(lag, pace_max) * 1e6)
        self._pending_demote = (worst, pace_us)
        self._demoted.add(worst)
        self._count("demote")
        self._last_line = (f"demote rank={worst} share={share:.2f} "
                           f"lag={lag * 1e3:.1f}ms")
        hlog.info(f"selfop: demoting rank {worst} (last arriver in "
                  f"{share * 100.0:.0f}% of {window} gathers, "
                  f"lag {lag * 1e3:.1f}ms)")
        return ("demote", -1)

    def take_pending_demote(self) -> Optional[Tuple[int, int]]:
        out = self._pending_demote
        self._pending_demote = None
        return out

    def status_line(self) -> str:
        parts = []
        if self._last_line:
            parts.append(self._last_line)
        v = _verdict.line()
        if v:
            parts.append(v)
        return "; ".join(parts)


_policy: Optional[SupervisionPolicy] = None


def ensure_policy(rank: int) -> SupervisionPolicy:
    global _policy
    if _policy is None:
        _policy = SupervisionPolicy(rank)
    else:
        _policy.rank = rank
    return _policy


def policy() -> Optional[SupervisionPolicy]:
    return _policy


def decision_counts() -> Dict[str, int]:
    return dict(_policy.decisions) if _policy is not None else {}


def cycle_pace_s(rank: int) -> float:
    """Per-cycle pacing sleep for non-demoted ranks under a demote verdict.

    Everyone *except* the demoted straggler waits a hair at the top of the
    cycle, so arrivals cluster instead of the whole world blocking on the
    straggler inside the gather.
    """
    v = _verdict
    if v.kind != "demote" or v.pace_us <= 0 or rank == v.target_rank:
        return 0.0
    return v.pace_us / 1e6


# ---------------------------------------------------------------------------
# Rejoin state sync at data-plane speed
# ---------------------------------------------------------------------------


def _sync_knobs() -> Tuple[int, str, int]:
    chunk = hconfig.env_int("HOROVOD_SELFOP_SYNC_CHUNK", 4 << 20)
    comp = hconfig.env_str("HOROVOD_SELFOP_SYNC_COMPRESSION", "none")
    min_bytes = hconfig.env_int("HOROVOD_SELFOP_SYNC_MIN_BYTES", 1 << 20)
    return max(64 << 10, chunk), comp, min_bytes


def _partition_state(values: Dict[str, object]):
    """Split state values into (arrays, scalars, legacy) manifest groups."""
    arrays: List[Tuple[str, str, Tuple[int, ...]]] = []
    scalars: List[Tuple[str, int, str]] = []
    legacy: List[str] = []
    for key in sorted(values):
        v = values[key]
        if (isinstance(v, np.ndarray) and v.flags.c_contiguous
                and not v.dtype.hasobject
                and np.dtype(v.dtype.str) == v.dtype):
            arrays.append((key, v.dtype.str, tuple(int(d) for d in v.shape)))
        elif type(v) in wire._SYNC_SCALAR_TYPES:
            scalars.append((key, wire._SYNC_SCALAR_TYPES[type(v)], repr(v)))
        else:
            legacy.append(key)
    return arrays, scalars, legacy


def _host_tree(rank: int, size: int, rank_table) -> Tuple[int, List[int]]:
    """Host-grouped broadcast tree rooted at rank 0.

    Host-roots (lowest rank on each host) are children of rank 0; every other
    rank is a child of its host-root.  Returns (parent, children) for `rank`.
    Falls back to a flat star on rank 0 when host info is unavailable.
    """
    hosts: Dict[int, str] = {}
    try:
        for r in range(size):
            entry = rank_table.get(r) if hasattr(rank_table, "get") else None
            if entry is None:
                continue
            host = entry[0] if isinstance(entry, (tuple, list)) else entry
            hosts[r] = str(host)
    except Exception:
        hosts = {}
    if len(hosts) != size:
        parent = 0 if rank != 0 else -1
        children = list(range(1, size)) if rank == 0 else []
        return parent, children
    roots: Dict[str, int] = {}
    for r in sorted(hosts):
        roots.setdefault(hosts[r], r)
    my_host = hosts[rank]
    my_root = roots[my_host]
    if rank == 0:
        parent = -1
    elif rank == my_root:
        parent = 0
    else:
        parent = my_root
    children = []
    if rank == 0:
        children = [r for h, r in sorted(roots.items()) if r != 0]
        children += [r for r in sorted(hosts) if hosts[r] == my_host
                     and r != 0 and roots[hosts[r]] == 0]
        children = sorted(set(children))
    elif rank == my_root:
        children = [r for r in sorted(hosts)
                    if hosts[r] == my_host and r != rank]
    return parent, children


def _accept_children(listener, expected: List[int], secret: bytes,
                     deadline: float) -> Dict[int, network.Channel]:
    out: Dict[int, network.Channel] = {}
    want = set(expected)
    while want:
        budget = deadline - time.monotonic()
        if budget <= 0:
            raise ConnectionError(
                f"selfop sync: children {sorted(want)} never connected")
        listener.settimeout(min(budget, 1.0))
        try:
            sock, addr = listener.accept()
        except (OSError, TimeoutError):
            continue
        ch = network.Channel(sock, secret, peer=str(addr))
        ch.arm(max(budget, 1.0), 1.0)
        tag, hello = ch.recv()
        if tag != SYNC_TAG or len(hello) != 4:
            ch.close()
            raise ConnectionError("selfop sync: bad hello frame")
        child = int.from_bytes(bytes(hello), "little")
        if child not in want:
            ch.close()
            raise ConnectionError(f"selfop sync: unexpected child {child}")
        want.discard(child)
        out[child] = ch
    return out


def _compress_chunk(view: np.ndarray, comp: str) -> np.ndarray:
    if comp == "bf16":
        f = view.view(np.float32)
        return (f.view(np.uint32) >> 16).astype(np.uint16)
    if comp == "fp16":
        return view.view(np.float32).astype(np.float16)
    return view


def _decompress_chunk(payload: np.ndarray, comp: str) -> np.ndarray:
    if comp == "bf16":
        u = payload.view(np.uint16).astype(np.uint32) << 16
        return u.view(np.float32)
    if comp == "fp16":
        return payload.view(np.float16).astype(np.float32)
    return payload


def sync_state(state) -> bool:
    """Chunked, zero-copy, tree-pipelined replacement for ``State.sync``.

    Returns True when the fast path ran (state committed), False when the
    caller should fall back to the legacy per-key broadcast.  The decline
    decision is world-consistent: the root broadcasts a zero-length manifest
    header when the state is too small or the fast path is disabled.
    """
    if not (_enabled() and hconfig.env_bool("HOROVOD_SELFOP_SYNC", True)):
        return False
    from horovod_tpu import ops

    from . import basics
    from . import elastic as helastic
    ctx = helastic.context()
    size = basics.size()
    rank = basics.rank()
    if ctx is None or size <= 1:
        return False
    rank_table = ctx.membership.rank_table
    if not rank_table:
        return False

    chunk_bytes, comp, min_bytes = _sync_knobs()
    gen = ctx.membership.generation
    t0 = time.monotonic()

    manifest = b""
    if rank == 0:
        arrays, scalars, legacy = _partition_state(state._values)
        total = sum(int(np.prod(shape or (1,))) * np.dtype(dt).itemsize
                    for _, dt, shape in arrays)
        if total >= min_bytes:
            my_host = ""
            entry = rank_table.get(0)
            if entry is not None:
                my_host = str(entry[0] if isinstance(entry, (tuple, list))
                              else entry)
            manifest = wire.serialize_selfop_sync(
                my_host, 0, gen, chunk_bytes, comp, arrays, scalars, legacy)

    # Round 1+2: manifest length then body, on the collective plane.
    hdr = np.array([len(manifest)], dtype=np.int64)
    hdr = ops.broadcast(hdr, root_rank=0, name=f"selfop.sync.hdr.g{gen}")
    n_manifest = int(hdr[0])
    if n_manifest == 0:
        return False  # world-consistent decline -> legacy path everywhere
    if rank == 0:
        mbuf = np.frombuffer(manifest, dtype=np.uint8)
    else:
        mbuf = np.zeros(n_manifest, dtype=np.uint8)
    mbuf = ops.broadcast(mbuf, root_rank=0, name=f"selfop.sync.manifest.g{gen}")
    info = wire.parse_selfop_sync(bytes(mbuf))
    arrays = info["arrays"]
    scalars = info["scalars"]
    legacy_keys = info["legacy"]
    chunk_bytes = info["chunk"]
    comp = info["compression"]

    # Round 3: everyone binds an ephemeral listener and allgathers its port,
    # so parents know where to reach children's hosts is unnecessary --
    # children dial parents, so parents only need their own listener; the
    # allgather gives rank 0's and the host-roots' ports to their children.
    listener = network.listen(0)
    my_port = listener.getsockname()[1]
    ports = ops.allgather(np.array([my_port], dtype=np.int64),
                          name=f"selfop.sync.ports.g{gen}")
    ports = [int(p) for p in np.asarray(ports).reshape(-1)]

    parent, children = _host_tree(rank, size, rank_table)
    secret = ctx.secret if isinstance(getattr(ctx, "secret", None), bytes) \
        else bytes(getattr(ctx, "secret", b"") or b"")
    deadline = time.monotonic() + max(
        30.0, float(getattr(ctx, "start_timeout", 60.0) or 60.0))

    up_ch: Optional[network.Channel] = None
    child_chs: Dict[int, network.Channel] = {}
    bytes_moved = 0
    try:
        if parent >= 0:
            entry = rank_table.get(parent)
            host = str(entry[0] if isinstance(entry, (tuple, list)) else entry)
            up_ch = network.connect(host, ports[parent], secret,
                                    timeout=10.0,
                                    retry_deadline=deadline - time.monotonic())
            up_ch.arm(max(deadline - time.monotonic(), 1.0), 1.0)
            up_ch.send(int(rank).to_bytes(4, "little"), SYNC_TAG)
        if children:
            child_chs = _accept_children(listener, children, secret, deadline)
        ordered = [child_chs[c] for c in sorted(child_chs)]

        for key, dtype_str, shape in arrays:
            dt = np.dtype(dtype_str)
            if rank == 0:
                arr = state._values[key]
            else:
                arr = np.empty(shape, dtype=dt)
            flat = arr.reshape(-1).view(np.uint8) if arr.size else \
                np.empty(0, dtype=np.uint8)
            nbytes = flat.nbytes
            compressible = comp in ("bf16", "fp16") and dt == np.float32
            off = 0
            while off < nbytes or (nbytes == 0 and off == 0):
                n = min(chunk_bytes, nbytes - off)
                dst = flat[off:off + n]
                if rank == 0:
                    if compressible and n:
                        payload = _compress_chunk(dst, comp)
                        for ch in ordered:
                            ch.sendv((payload,), SYNC_TAG)
                        # keep the root bit-coherent with what the fleet got
                        dst[:] = _decompress_chunk(payload, comp) \
                            .view(np.uint8)[:n]
                    else:
                        for ch in ordered:
                            ch.sendv((dst,), SYNC_TAG)
                else:
                    if compressible and n:
                        wire_n = n // 2
                        buf = np.empty(wire_n, dtype=np.uint8)
                        tag, got = up_ch.recv_into(memoryview(buf))
                        if tag != SYNC_TAG or got != wire_n:
                            raise ConnectionError(
                                "selfop sync: short compressed chunk")
                        for ch in ordered:
                            ch.sendv((buf,), SYNC_TAG)
                        dst[:] = _decompress_chunk(buf, comp).view(np.uint8)[:n]
                    else:
                        if n:
                            # Interior/leaf leg: cut-through relay —
                            # chunks stream to the children while still
                            # arriving from the parent (native
                            # hvd_relay_frame when built, store-and-
                            # forward fallback otherwise).
                            from . import controller as hcontroller
                            got = hcontroller.relay_frame_into(
                                up_ch, ordered, SYNC_TAG, dst)
                            if got != n:
                                raise ConnectionError(
                                    "selfop sync: short chunk")
                        else:
                            for ch in ordered:
                                ch.sendv((dst,), SYNC_TAG)
                bytes_moved += n
                off += n
                if nbytes == 0:
                    break
            if rank != 0:
                state._values[key] = arr

        # Scalars install identically everywhere straight from the manifest.
        for key, stype, rep in scalars:
            state._values[key] = wire._SYNC_SCALAR_CTORS[stype](rep)
        for ch in ordered:
            ch.send(b"", SYNC_TAG)  # done marker: children may close
        if up_ch is not None:
            tag, fin = up_ch.recv()
            if tag != SYNC_TAG or len(fin) != 0:
                raise ConnectionError("selfop sync: bad done marker")
    finally:
        for ch in child_chs.values():
            try:
                ch.close()
            except Exception:
                pass
        if up_ch is not None:
            try:
                up_ch.close()
            except Exception:
                pass
        try:
            listener.close()
        except Exception:
            pass

    # Anything we couldn't describe on the wire rides the legacy broadcast.
    if legacy_keys:
        state._sync_broadcast(legacy_keys)
    state.commit()
    dt_s = time.monotonic() - t0
    try:
        ctx.note_sync(dt_s, bytes_moved)
    except Exception:
        pass
    hlog.info(f"selfop sync: {len(arrays)} arrays, {len(scalars)} scalars, "
              f"{len(legacy_keys)} legacy keys, {bytes_moved / 2**20:.1f} MiB "
              f"in {dt_s:.2f}s (gen {gen})")
    return True


# ---------------------------------------------------------------------------
# Async sharded checkpoints on idle cycles
# ---------------------------------------------------------------------------

_ckpt_state = None
_ckpt_last_seq = -1
_ckpt_last_bucket = -1
_ckpt_last_wall = 0.0
# The bookkeeping above is touched from the background loop
# (maybe_checkpoint), the writer thread (_write_shard) and the main
# thread (restore_state at run() entry, reset in tests).
_ckpt_lock = lockdep.lock("selfop._ckpt_lock")


def _ckpt_dir() -> str:
    return hconfig.env_str("HOROVOD_SELFOP_CKPT_DIR", "")


def checkpoint_dir() -> str:
    """The async-checkpoint directory, empty when the feature is off."""
    return _ckpt_dir() if _enabled() else ""


def register_state(state) -> None:
    """Make `state` the async-checkpoint subject (no-op without a dir)."""
    global _ckpt_state
    if _ckpt_dir():
        _ckpt_state = state


def checkpoint_age_s() -> float:
    if _ckpt_last_wall <= 0.0:
        return -1.0
    return max(0.0, time.time() - _ckpt_last_wall)


def _shard_paths(directory: str, seq: int, rank: int, world: int):
    stem = f"shard_s{seq}_r{rank}_of_{world}"
    return (os.path.join(directory, stem + ".npz"),
            os.path.join(directory, stem + ".json"))


def _write_shard(committed: Dict[str, object], seq: int, rank: int,
                 world: int, directory: str) -> None:
    try:
        os.makedirs(directory, exist_ok=True)
        keys = sorted(committed)
        mine = [k for i, k in enumerate(keys) if i % world == rank]
        arrays: Dict[str, np.ndarray] = {}
        scalars: Dict[str, List] = {}
        skipped: List[str] = []
        for k in mine:
            v = committed[k]
            if isinstance(v, np.ndarray) and not v.dtype.hasobject:
                arrays[k] = v
            elif type(v) in wire._SYNC_SCALAR_TYPES:
                scalars[k] = [wire._SYNC_SCALAR_TYPES[type(v)], repr(v)]
            else:
                skipped.append(k)
        npz_path, json_path = _shard_paths(directory, seq, rank, world)
        tmp_npz = npz_path + ".tmp"
        with open(tmp_npz, "wb") as fh:
            np.savez(fh, **arrays)
        os.replace(tmp_npz, npz_path)
        digest = hashlib.sha256()
        with open(npz_path, "rb") as fh:
            for block in iter(lambda: fh.read(1 << 20), b""):
                digest.update(block)
        meta = {
            "seq": seq, "rank": rank, "world": world,
            "sha256": digest.hexdigest(),
            "arrays": sorted(arrays),
            "scalars": scalars,
            "skipped": skipped,
            "wall": time.time(),
        }
        tmp_json = json_path + ".tmp"
        with open(tmp_json, "w", encoding="utf-8") as fh:
            json.dump(meta, fh)
        os.replace(tmp_json, json_path)
        _prune_shards(directory, rank)
        global _ckpt_last_wall
        with _ckpt_lock:
            _ckpt_last_wall = time.time()
    except Exception as err:  # background writer: never take the run down
        hlog.warning(f"selfop checkpoint: shard write failed: {err}")


def _prune_shards(directory: str, rank: int) -> None:
    keep = hconfig.env_int("HOROVOD_SELFOP_CKPT_KEEP", 3)
    mine: List[Tuple[int, str]] = []
    for name in os.listdir(directory):
        m = _SHARD_RE.match(name)
        if m and int(m.group(2)) == rank:
            mine.append((int(m.group(1)), name))
    mine.sort(reverse=True)
    for seq, name in mine[keep:]:
        stem = name[:-len(".json")]
        for suffix in (".json", ".npz"):
            try:
                os.remove(os.path.join(directory, stem + suffix))
            except OSError:
                pass


def maybe_checkpoint(rank: int, size: int, idle: bool) -> None:
    """Persist this rank's shard of the committed state if it is due.

    Wall-clock interval buckets keep the ranks loosely aligned on the same
    commit without any extra collective: commits are produced by synchronized
    training steps, and the restore path tolerates ragged tails by falling
    back to the newest *complete* sequence.
    """
    global _ckpt_last_seq, _ckpt_last_bucket
    state = _ckpt_state
    if state is None or not _enabled():
        return
    directory = _ckpt_dir()
    if not directory:
        return
    interval = max(1.0, hconfig.env_float("HOROVOD_SELFOP_CKPT_INTERVAL", 30.0))
    bucket = int(time.time() / interval)
    with _ckpt_lock:
        if bucket <= _ckpt_last_bucket:
            return
        if not idle and _ckpt_last_bucket >= 0 \
                and bucket - _ckpt_last_bucket < 2:
            return  # busy cycle: force a write only when >= 2 buckets stale
        seq = getattr(state, "_commit_seq", 0)
        if seq == _ckpt_last_seq:
            return
        committed = state._committed  # commit() replaces wholesale: safe ref
        _ckpt_last_seq = seq
        _ckpt_last_bucket = bucket
    from ..utils import checkpoint as uckpt
    pool = uckpt._writer_pool()
    fut = pool.submit(_write_shard, committed, seq, rank, size, directory)
    with uckpt._pending_lock:
        uckpt._pending.append(fut)


def restore_state(state, directory: str) -> Optional[int]:
    """Restore `state` from the newest complete shard set in `directory`.

    Returns the restored commit sequence, or None when no complete,
    digest-clean set exists.
    """
    from ..utils import checkpoint as uckpt
    uckpt.wait_pending_saves()
    if not os.path.isdir(directory):
        return None
    by_seq: Dict[int, Dict[int, Tuple[str, int]]] = {}
    for name in os.listdir(directory):
        m = _SHARD_RE.match(name)
        if not m:
            continue
        seq, rank, world = int(m.group(1)), int(m.group(2)), int(m.group(3))
        by_seq.setdefault(seq, {})[rank] = (name, world)
    for seq in sorted(by_seq, reverse=True):
        shards = by_seq[seq]
        worlds = {w for _, w in shards.values()}
        if len(worlds) != 1:
            continue
        world = worlds.pop()
        if set(shards) != set(range(world)):
            continue
        loaded: Dict[str, object] = {}
        ok = True
        for rank in range(world):
            json_path = os.path.join(directory, shards[rank][0])
            npz_path = json_path[:-len(".json")] + ".npz"
            try:
                with open(json_path, "r", encoding="utf-8") as fh:
                    meta = json.load(fh)
                digest = hashlib.sha256()
                with open(npz_path, "rb") as fh:
                    for block in iter(lambda: fh.read(1 << 20), b""):
                        digest.update(block)
                if digest.hexdigest() != meta["sha256"]:
                    raise ValueError("digest mismatch")
                with np.load(npz_path, allow_pickle=False) as zf:
                    for k in zf.files:
                        loaded[k] = zf[k]
                for k, (stype, rep) in meta.get("scalars", {}).items():
                    loaded[k] = wire._SYNC_SCALAR_CTORS[int(stype)](rep)
            except Exception as err:
                hlog.warning(f"selfop restore: seq {seq} shard {rank} "
                             f"unusable ({err}); trying older")
                ok = False
                break
        if not ok:
            continue
        state._values.update(loaded)
        state.commit()
        # the restored snapshot IS commit `seq`: stamp it after the
        # commit bump so maybe_checkpoint won't rewrite an identical shard
        object.__setattr__(state, "_commit_seq", seq)
        global _ckpt_last_seq
        with _ckpt_lock:
            _ckpt_last_seq = seq
        hlog.info(f"selfop restore: resumed {len(loaded)} keys from "
                  f"seq {seq} (world {world})")
        return seq
    return None


# ---------------------------------------------------------------------------
# Test hook
# ---------------------------------------------------------------------------


def reset() -> None:
    """Reset module state between tests (signal handler stays installed)."""
    global _verdict, _policy, _ckpt_state, _ckpt_last_seq
    global _ckpt_last_bucket, _ckpt_last_wall, _grace_timer, _wake_cb
    _verdict = SupervisionVerdict()
    _policy = None
    _ckpt_state = None
    with _ckpt_lock:
        _ckpt_last_seq = -1
        _ckpt_last_bucket = -1
        _ckpt_last_wall = 0.0
    _preempt.clear()
    if _grace_timer is not None:
        _grace_timer.cancel()
        _grace_timer = None
    _wake_cb = None
