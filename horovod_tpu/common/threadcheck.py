"""Runtime thread-affinity sanitizer: ``HOROVOD_TPU_THREADCHECK``.

The dynamic half of hvdlint's static ``thread-ownership`` analyzer
(see docs/static_analysis.md), built exactly like lockdep: the static
pass proves what its resolver can follow; this sanitizer observes
what actually runs — callback indirection, monkeypatched seams,
thread hops the call graph hides.

Design: long-lived threads **register a role** at their entry point
(the same role names the static analyzer derives from the spawn
site's ``Thread(name=...)``: ``hvd-background``, ``hvd-overlap``,
``hvd-worldtrace-writer``, ...; unregistered threads — including the
user's — are ``main``). A handful of **checked fields** (the same
``module.Class.attr`` ids the analyzer reports) are wrapped in a
write-intercepting descriptor. The rule mirrors the analyzer's:

* the FIRST write to a field on an object is free — that is
  constructor initialization, published to every later thread by
  ``Thread.start()``'s happens-before;
* after that, a write is legal when it comes from the field's owning
  role, or from any role while a lockdep-tracked lock is held (the
  runtime's witness for "synchronized");
* anything else raises :class:`ThreadAffinityError` naming the field,
  the owning role and the trespassing role (``warn`` mode logs and
  counts instead — production triage). Either mode feeds
  ``hvd_threadcheck_violations_total`` on the metrics plane, mirrored
  by the runtime collector next to the lockcheck counter.

Fields declared without a fixed owner track the LAST legal writer as
owner — right for handoff fields like ``Runtime._tenant_lane`` whose
ownership legitimately migrates under its lock.

Modes:

* ``HOROVOD_TPU_THREADCHECK=1`` (or ``raise``/``on``/``true``) —
  raise at the violating write. Armed in the multiprocess test
  worlds, so every mp scenario doubles as an affinity regression
  test.
* ``HOROVOD_TPU_THREADCHECK=warn`` — log + count, never raise.
* unset/empty — :func:`install` leaves the class untouched: checked
  fields stay plain instance attributes (zero steady-state overhead;
  the would-be sites are enumerable via :func:`sites` so a test can
  assert the no-op).

Arming threadcheck implicitly arms lockdep in ``warn`` mode when
``HOROVOD_TPU_LOCKCHECK`` is unset: the "held lock" witness comes
from lockdep's per-thread stack, which plain (unwrapped) locks never
feed — without it every lock-protected cross-role write would be a
false positive.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import lockdep


class ThreadAffinityError(RuntimeError):
    """Unsynchronized cross-role write to a checked field."""


_MODE_MAP = {"1": "raise", "true": "raise", "on": "raise",
             "raise": "raise", "warn": "warn"}
_mode: Optional[str] = None          # None = env not read yet
_violations = 0
_count_lock = threading.Lock()
_tls = threading.local()

# Every field ever handed to install(), armed or not — the test
# surface for "unarmed means untouched": (cls, attr, field_id, owner).
_SITES: List[Tuple[type, str, str, Optional[str]]] = []

MAIN_ROLE = "main"
_OWNER_PREFIX = "_tc_owner::"


def _get_mode() -> str:
    global _mode
    if _mode is None:
        raw = hconfig.env_str(
            "HOROVOD_TPU_THREADCHECK", "").strip().lower()
        # hvdlint: owned-by=main -- idempotent lazy cache of one env read: every racing writer stores the same value, and reset() is test-only
        _mode = _MODE_MAP.get(raw, "")
    return _mode


def enabled() -> bool:
    return bool(_get_mode())


def violation_count() -> int:
    """Lifetime observed violations (mirrored to the metrics plane as
    hvd_threadcheck_violations_total by the runtime's collector)."""
    return _violations


def register_role(role: str) -> None:
    """Adopt ``role`` for the calling thread — one line at the top of
    each long-lived thread's entry point. No-op when unarmed."""
    if _get_mode():
        _tls.role = role


def current_role() -> str:
    return getattr(_tls, "role", MAIN_ROLE)


def sites() -> List[Tuple[type, str, str, Optional[str]]]:
    """All registered checked-field sites, armed or not."""
    return list(_SITES)


def _violate(msg: str) -> None:
    global _violations
    with _count_lock:
        _violations += 1
    if _get_mode() == "raise":
        raise ThreadAffinityError(msg)
    from horovod_tpu.common import logging as hlog
    hlog.warning(f"threadcheck: {msg}")


class _Checked:
    """Write-intercepting data descriptor for one checked field.

    Values live in the instance ``__dict__`` under the ATTRIBUTE'S OWN
    name: objects built before arming keep working after a test
    re-arms (their plain attribute becomes the descriptor's backing
    slot), and stripping the descriptor hands the attribute straight
    back to normal lookup."""

    __slots__ = ("attr", "field_id", "fixed_owner", "owner_slot")

    def __init__(self, attr: str, field_id: str,
                 fixed_owner: Optional[str]):
        self.attr = attr
        self.field_id = field_id
        self.fixed_owner = fixed_owner
        self.owner_slot = _OWNER_PREFIX + attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            return obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None

    def __set__(self, obj, value) -> None:
        d = obj.__dict__
        role = current_role()
        if self.attr not in d:
            # First write: constructor init, published to every
            # thread the owner starts afterwards (Thread.start
            # happens-before). Record nothing for fixed-owner fields;
            # seed migrating ones with the declared start.
            d[self.owner_slot] = self.fixed_owner or role
        else:
            owner = d.get(self.owner_slot, MAIN_ROLE)
            if role != owner and not lockdep._held():
                self._violation(owner, role)
            elif self.fixed_owner is None:
                d[self.owner_slot] = role
        d[self.attr] = value

    def __delete__(self, obj) -> None:
        obj.__dict__.pop(self.attr, None)
        obj.__dict__.pop(self.owner_slot, None)

    def _violation(self, owner: str, role: str) -> None:
        _violate(
            f"field '{self.field_id}' is owned by role '{owner}' but "
            f"thread '{threading.current_thread().name}' (role "
            f"'{role}') rebinds it with no lock held — take the "
            f"owning lock, or fix the ownership story (see "
            f"docs/troubleshooting.md)")


def install(cls: type, attr: str, field_id: str,
            owner: Optional[str] = None) -> None:
    """Declare ``cls.attr`` a checked field named ``field_id`` (the
    static analyzer's ``module.Class.attr`` id). ``owner`` pins the
    owning role; None lets ownership migrate with each legal write.
    Called at module import right after the class body; when unarmed
    this records the site and touches NOTHING — the attribute stays a
    plain instance attribute."""
    _SITES.append((cls, attr, field_id, owner))
    if _get_mode():
        setattr(cls, attr, _Checked(attr, field_id, owner))


def reset(mode: Optional[str] = None) -> None:
    """Tests only: drop the counter, force (or re-read) the mode, and
    re-apply or strip the descriptors across every registered site."""
    global _mode, _violations
    with _count_lock:
        _violations = 0
    _mode = _MODE_MAP.get(mode, "") if mode is not None else None
    armed = bool(_get_mode())
    for cls, attr, field_id, owner in _SITES:
        current = cls.__dict__.get(attr)
        if armed and not isinstance(current, _Checked):
            setattr(cls, attr, _Checked(attr, field_id, owner))
        elif not armed and isinstance(current, _Checked):
            delattr(cls, attr)
