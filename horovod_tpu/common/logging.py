"""Leveled logging with per-rank prefix.

Python equivalent of the reference's stream-style C++ ``LOG(LEVEL, rank)``
macros (reference: horovod/common/logging.h:52-53). Level comes from
``HOROVOD_LOG_LEVEL`` (trace/debug/info/warning/error/fatal) and timestamps
can be hidden with ``HOROVOD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import sys
import time

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import lockdep

TRACE, DEBUG, INFO, WARNING, ERROR, FATAL = range(6)

_LEVEL_NAMES = ["trace", "debug", "info", "warning", "error", "fatal"]
_lock = lockdep.lock("logging._lock")


def _min_level() -> int:
    name = hconfig.env_str("HOROVOD_LOG_LEVEL", "warning").lower()
    try:
        return _LEVEL_NAMES.index(name)
    except ValueError:
        return WARNING


_min = _min_level()


def reset_level() -> None:
    """Re-read HOROVOD_LOG_LEVEL (used by tests)."""
    global _min
    _min = _min_level()


def set_level(name: str) -> None:
    """Set the level programmatically (Config.log_level is applied via
    this at init)."""
    global _min
    try:
        _min = _LEVEL_NAMES.index(name.lower())
    except ValueError:
        _min = WARNING


def log(level: int, msg: str, rank: int | None = None) -> None:
    if level < _min:
        return
    parts = []
    if not hconfig.env_bool("HOROVOD_LOG_HIDE_TIME", False):
        t = time.time()
        parts.append(time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))
                     + ".%06d" % int((t % 1) * 1e6))
    if rank is not None:
        parts.append("[%d]" % rank)
    parts.append("[%s]" % _LEVEL_NAMES[level].upper())
    line = " ".join(parts) + " " + msg + "\n"
    with _lock:
        sys.stderr.write(line)
        sys.stderr.flush()


def trace(msg, rank=None):
    log(TRACE, msg, rank)


def debug(msg, rank=None):
    log(DEBUG, msg, rank)


def info(msg, rank=None):
    log(INFO, msg, rank)


def warning(msg, rank=None):
    log(WARNING, msg, rank)


def error(msg, rank=None):
    log(ERROR, msg, rank)
