"""Environment-variable configuration surface.

The reference reads all knobs from ``HOROVOD_*`` env vars once at
background-thread startup (reference: horovod/common/operations.cc:626-639
helpers and 792-871). We keep the exact same names so scripts tuned for
the reference carry over, plus ``HOROVOD_TPU_*`` extensions for the
TPU-specific machinery.

This module is the ONLY place the runtime reads the environment —
enforced by ``python -m tools.hvdlint`` (the ``knobs`` analyzer):
modules that need a knob outside a ``Config`` snapshot (module-level
singletons, the launcher's child-env plumbing) go through the public
``env_str``/``env_int``/``env_float``/``env_bool`` helpers so
defaults, truthiness rules and the documentation contract stay in one
place.
"""

from __future__ import annotations

import dataclasses
import os


def env_str(name: str, default: str = "") -> str:
    v = os.environ.get(name)
    return default if v is None or v == "" else v


def env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def env_bool(name: str, default: bool) -> bool:
    # Reference semantics: set and == "1" → on (operations.cc:626-631).
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v.strip() in ("1", "true", "True", "TRUE", "yes", "on")


# Internal aliases kept for the from_env body below.
_env_int = env_int
_env_float = env_float
_env_bool = env_bool


@dataclasses.dataclass
class Config:
    """Snapshot of all runtime knobs, read once at init.

    Defaults follow the reference: 64 MiB fusion threshold
    (operations.cc:807-812), 5 ms cycle time (operations.cc:815-820),
    60 s stall check (operations.cc:543-624).
    """

    # Tensor fusion (reference: operations.cc:424-446, 807-820)
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 5.0

    # Steady-state negotiation fast path (reference: the bit-vector
    # response cache upstream added as its coordinator scalability fix,
    # HOROVOD_CACHE_CAPACITY): previously negotiated responses are
    # kept in a world-coherent LRU cache and steady-state cycles
    # exchange one bit per cache slot instead of serialized Request
    # lists. Capacity 0 or HOROVOD_CACHE_ENABLED=0 disables (dynamic
    # graphs that never repeat tensor signatures gain nothing from
    # it). Both knobs must be identical on every rank.
    cache_enabled: bool = True
    cache_capacity: int = 1024
    # Fused speculative cycle: in bitmask steady state a rank attaches
    # its pre-packed fused allreduce buffers to the hit-mask gather
    # frame; the coordinator reduces inline and broadcasts grant +
    # result in one response frame — negotiation and the data plane
    # collapse into ONE world round-trip per step. Opportunistic and
    # per-cycle: any deviation (new tensor, shape change, a rank with
    # this knob off) falls back to the classic two-round cached path
    # for that cycle, so ranks may disagree on this knob safely.
    # Applies only when the star socket data plane would carry the
    # batch anyway (shm/ring/XLA-bound batches keep their plane).
    cache_speculative: bool = True

    # Zero-copy native data plane (docs/performance.md): steady-state
    # payloads move straight between sockets and numpy memory — the
    # persistent fusion arena feeds vectored sendmsg/recvmsg
    # (hvd_sendv/hvd_recv_into), receive sides land in preallocated
    # arrays, and the fused speculative cycle runs as ONE native call
    # per step (hvd_steady_cycle family). HOROVOD_TPU_ZERO_COPY=0
    # restores the PR 3 byte-copy paths (A/B lever for
    # collective_bench --steady-only; heterogeneous worlds are safe —
    # the wire format is identical either way).
    zero_copy: bool = True

    # Batched-submission reactor (docs/performance.md Layer 6): the
    # coordinator's N per-cycle peer recvs collapse into ONE native
    # readiness loop (hvd_gather_frames_batched — io_uring when the
    # build and kernel both have it, poll(2) otherwise, byte-identical
    # either way), and the hierarchical root/leaf relay legs switch to
    # the chunked cut-through relay (hvd_relay_frame).
    # HOROVOD_TPU_REACTOR=0 restores the sequential recv loop and the
    # store-and-forward relay; heterogeneous worlds are safe — the
    # wire format is identical either way.
    reactor: bool = True

    # Frames at or above this many payload bytes go out via
    # MSG_ZEROCOPY (kernel pins the pages instead of copying them into
    # the socket buffer; completion notifications are drained before
    # the send returns). Below it the plain copying send wins — the
    # pin/notify overhead beats the copy only for large frames.
    # 0 disables zerocopy sends entirely; the
    # hvd_zerocopy_copied_total counter surfaces kernels/paths that
    # silently degrade to copying (loopback always does).
    zerocopy_send_threshold: int = 64 * 1024

    # Ring data plane for the socket backend (TPU-native extension): host
    # payloads at or above this size ride the bandwidth-optimal 2-phase
    # ring (ops/ring.py) instead of the star through rank 0 — the TCP
    # rendering of what MPI_Allreduce gives the reference internally
    # (reference: mpi_operations.cc:25-84). Small messages stay on the
    # star (2 hops beats 2(N-1) lockstep hops when latency dominates,
    # the same size-based algorithm switch MPI/NCCL make internally).
    # Needs >= 3 ranks; -1 disables.
    ring_threshold_bytes: int = 1024 * 1024

    # Shared-memory data plane for same-host worlds (TPU-native rendering
    # of the reference's MPI_Win_allocate_shared staging,
    # mpi_operations.cc:179-329). HOROVOD_TPU_SHM=0 forces sockets.
    shm_enabled: bool = True

    # Wire-dtype gradient compression (docs/performance.md; upstream
    # analog: the Compression API's fp16-on-the-wire, deepened into a
    # negotiated per-request attribute — common/wire_dtype.py). This
    # rank PROPOSES the value for every float32/float64 allreduce; the
    # coordinator resolves the world's common denominator per fused
    # batch and broadcasts it in the Response, so heterogeneous knobs
    # degrade to the least aggressive proposal instead of diverging.
    # none | bf16 (recommended on TPU hosts: f32's exponent range at
    # half the bytes) | fp16 | int8 (with per-tensor error-feedback
    # residuals, Deep Gradient Compression style).
    compression: str = "none"

    # Overlap tier (docs/performance.md Layer 5): bucketed ready-order
    # dispatch + asynchronous in-flight steady cycles that hide
    # collective wire time under backward compute (DDP-bucket /
    # ByteScheduler lineage). HOROVOD_OVERLAP_BUCKETS splits every
    # grouped allreduce into that many size-balanced buckets (0 =
    # derive from HOROVOD_OVERLAP_BYTES; both 0 = bucketing off), each
    # negotiated and reduced as its OWN fused speculative / native
    # zero-copy cycle, so early buckets ride the wire while the
    # training thread still computes later gradients.
    # HOROVOD_OVERLAP_BYTES is the target bucket payload size when
    # deriving the count. All knobs are rank-local scheduling only —
    # the wire protocol is unchanged, so heterogeneous worlds degrade
    # to the synchronous path instead of diverging.
    overlap_buckets: int = 0
    overlap_bucket_bytes: int = 0
    # Asynchronous in-flight steady cycles: up to this many zero-copy
    # native steady cycles may be outstanding on the overlap runner
    # thread while the background loop packs the next bucket and the
    # training thread computes (handles complete out of band;
    # synchronize() only blocks on the tail bucket). 0 keeps every
    # cycle synchronous in the background loop. Needs the native
    # zero-copy plane; falls back silently without it.
    overlap_inflight: int = 2
    # Chunked pipelined transfer: the native steady worker splits a
    # compressed fused arena into wire chunks of this size and
    # interleaves the hvd_cast compression of chunk i+1 with the
    # kernel-buffered transmission of chunk i (one fused cast+HMAC
    # pass when frame auth is armed). 0 disables the chunk loop.
    overlap_chunk_bytes: int = 1024 * 1024

    # Two-level hierarchical allreduce (intra-host shm reduce ->
    # cross-host ring among local roots -> intra-host shm broadcast;
    # reference analog: NCCLHierarchicalAllreduce). HOROVOD_TWO_LEVEL=1
    # stamps multi-host allreduce batches at or above
    # two_level_threshold_bytes with the two-level algorithm; default
    # off keeps the existing shm-hier/star/ring routing untouched.
    # With HOROVOD_AUTOTUNE=1 the per-bucket (algorithm, wire dtype)
    # choice is tuned instead (common/parameter_manager.py).
    two_level: bool = False
    two_level_threshold_bytes: int = 0

    # ICI-native data plane (HOROVOD_TPU_ICI=1): fused allreduce
    # batches stamped ALG_ICI pack/prescale/cast on-device through ONE
    # pre-compiled fused-psum XLA executable over the local device mesh
    # (ops/xla_ops.py IciPlane), then ride the existing compressed
    # socket/ring plane for the cross-slice (DCN) leg. Requires >= 2
    # local devices (ici_devices caps how many the plane meshes over; 0
    # = all visible). The capability is world-AND-agreed at init so
    # heterogeneous worlds degrade to the socket plane consistently.
    # With HOROVOD_AUTOTUNE=1, ALG_ICI instead joins the per-bucket
    # discrete grid; without it, HOROVOD_TPU_ICI_THRESHOLD gates the
    # static stamp by fused-batch size.
    ici_enabled: bool = False
    ici_devices: int = 0
    ici_threshold_bytes: int = 0

    # Idle backoff for the background loop (TPU-native extension): after
    # a grace period of empty cycles the negotiation sleep ramps toward
    # this cap instead of waking every cycle_time_ms forever; enqueue
    # snaps it awake immediately. 0 disables (reference behavior).
    idle_backoff_ms: float = 25.0

    # Hierarchical collectives (reference: operations.cc:822-841); on TPU
    # this selects ICI×DCN mesh-axis-factored collectives (read by the
    # spmd hierarchical helpers; the flat TCP/XLA backends ignore it).
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False

    # Hierarchical CONTROL plane (TPU-native extension): on multi-host
    # worlds, each remote host's leaf ranks deliver their per-cycle
    # RequestLists to the host's local root, which forwards ONE
    # aggregate frame to the coordinator (and relays responses back),
    # so coordinator fan-in scales with n_hosts instead of world size —
    # the control-plane analog of the tree gather MPI_Gather gives the
    # reference for free (reference: operations.cc:1044-1065).
    # HOROVOD_TPU_HIER_CONTROLLER=0 forces the flat star.
    hier_controller: bool = True

    # XLA broadcast rendering: "psum" (masked psum — one fused
    # allreduce, ~2x payload per link but single-round and pipelined
    # by XLA; measured fastest at N>=8) or "tree" (binary-tree
    # ppermute chain — each device receives the payload exactly once,
    # N-1 payload transfers over the whole fabric vs the psum's ~2N,
    # at ceil(log2 N) sequential rounds of latency; wins on small or
    # congested worlds). See benchmarks/collective_bench.py
    # broadcast_rendering.
    xla_broadcast: str = "psum"

    # Timeline (reference: operations.cc:792-798)
    timeline_path: str = ""
    timeline_mark_cycles: bool = False

    # World trace plane (TPU-native extension; docs/tracing.md).
    # HOROVOD_TPU_TRACE=<path> arms clock-aligned cross-rank tracing:
    # every rank batches its cycle/exec spans into TAG_TRACE frames
    # that ride the control tree out-of-band like METRICS frames, and
    # rank 0 writes ONE merged Chrome-trace file at <path> with a
    # track per rank, timestamps corrected into the coordinator clock
    # and the world cycle number on every span. Must be set on every
    # rank (hvdtpurun --trace plumbs it). Empty disables — the
    # disabled path installs only no-op collector hooks.
    # (The flight recorder is separate and ON by default:
    # HOROVOD_TPU_FLIGHT / _FLIGHT_EVENTS / _FLIGHT_DIR are read by
    # common/trace.py at first use, deliberately not Config fields —
    # the recorder must survive elastic re-inits, like lockdep.)
    trace_path: str = ""
    trace_interval_s: float = 1.0

    # Metrics plane (TPU-native extension; the reference has no live
    # observability at all — timeline/stall/autotune are post-hoc).
    # HOROVOD_TPU_METRICS=1 arms per-rank counters/gauges/histograms
    # across the runtime, controller and op backends, world-aggregated
    # over the control tree every metrics_interval_s seconds. Default
    # OFF: the disabled path installs only no-op hooks (the
    # _NoOpTimeline pattern) so steady-state cost is zero.
    metrics_enabled: bool = False
    metrics_interval_s: float = 5.0
    # Rank-0 Prometheus endpoint: GET /metrics in text exposition
    # format. -1 disables the HTTP server; 0 binds an ephemeral port
    # (readable via horovod_tpu.metrics()["http_port"]).
    metrics_port: int = -1
    # Bind address for the endpoint. Default all interfaces (the
    # exporter convention — Prometheus usually scrapes from another
    # host); the endpoint is UNAUTHENTICATED, so on shared networks
    # set HOROVOD_TPU_METRICS_ADDR=127.0.0.1 and tunnel/proxy.
    metrics_addr: str = ""
    # Rank-0 JSONL snapshot log: one world-aggregated snapshot line
    # per interval. Empty disables.
    metrics_log: str = ""

    # Async collective completion (reference: cuda_operations.cc:148-179
    # detached finalizer threads + Status::InProgress). Off = the cycle
    # loop blocks until each collective's outputs are ready.
    async_completion: bool = True

    # Stall detection (reference: operations.cc:543-624)
    stall_check_disable: bool = False
    stall_check_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0

    # Runtime lockdep (HOROVOD_TPU_LOCKCHECK, docs/static_analysis.md)
    # is deliberately NOT a Config field: module-level locks exist
    # before any Config snapshot does, so common/lockdep.py reads the
    # knob once at first lock creation via env_str — a field here would
    # be an inert second source of truth.

    # Fail-fast liveness (TPU-native extension; the reference has no
    # peer-death detection — a SIGKILL'd rank leaves peers blocked in
    # MPI forever until the launcher kills the world). PING frames ride
    # idle gather waits every heartbeat_interval_s; a control channel
    # silent for heartbeat_timeout_s is declared dead and the world
    # aborts with WorldAbortedError. timeout <= 0 disables detection
    # (reference behavior).
    heartbeat_interval_s: float = 5.0
    heartbeat_timeout_s: float = 30.0

    # Autotune (reference: operations.cc:862-871, parameter_manager.cc)
    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8

    # Logging (reference: logging.h, HOROVOD_LOG_LEVEL)
    log_level: str = "warning"
    log_hide_time: bool = False

    # Control plane (TPU-native: TCP coordination service instead of MPI).
    # Rendezvous address of the rank-0 coordinator.
    controller_addr: str = ""
    controller_port: int = 0
    # Inherited fd of a pre-bound coordinator listener (socket-activation
    # style): the launcher's TaskServer reserves the port and passes the
    # open socket to rank 0, so the endpoint it published can never be
    # stolen between reservation and bind.
    controller_fd: int = -1
    secret_key: str = ""
    start_timeout: float = 30.0

    # Native C++ core (horovod_tpu/native). On by default when the shared
    # library is importable; HOROVOD_TPU_NATIVE=0 forces pure-Python.
    native_core: bool = True

    # Elastic worlds (docs/fault_tolerance.md; upstream analog: Elastic
    # Horovod, v0.20). HOROVOD_ELASTIC=1 makes WorldAbortedError
    # recoverable: survivors re-rendezvous into a shrunk world within
    # elastic_window_s seconds (coordinator re-elected from the lowest
    # surviving rank when rank 0 died), respawned workers rejoin at
    # the next barrier, and training resumes after an
    # hvd.elastic.State re-broadcast. Below elastic_min_world members
    # the job aborts for real. Default OFF: the PR 2 fail-fast
    # behavior is untouched.
    elastic_enabled: bool = False
    elastic_window_s: float = 30.0
    elastic_min_world: int = 1
    # Fixed port for this rank's elastic listener (0 = ephemeral). The
    # launcher pins rank 0's so the join endpoint it advertises to
    # respawned workers stays stable across resizes.
    elastic_port: int = 0
    # Joiner identity (exported by the hvdtpurun --elastic supervision
    # loop on respawn): dial this elastic endpoint instead of the
    # normal HOROVOD_CONTROLLER_ADDR/PORT rendezvous.
    elastic_join: bool = False
    elastic_join_addr: str = ""
    elastic_join_port: int = 0

    # Self-operation (docs/fault_tolerance.md, common/selfop.py):
    # telemetry-driven supervision, preemption drains, the data-plane
    # rejoin sync and async in-cycle checkpoints. Like lockdep and the
    # flight recorder, the HOROVOD_SELFOP* / HOROVOD_PREEMPT* knobs
    # are deliberately NOT Config fields: the supervision policy,
    # signal handler and checkpoint writer are process-lifetime
    # singletons that must survive elastic re-inits, so selfop.py
    # reads them through the env_* helpers at use sites. The launcher
    # restart budget (HOROVOD_TPU_ELASTIC_RESTARTS) likewise lives in
    # run/launch.py — it configures the supervising parent, never a
    # rank.

    # Multi-tenant collective service (docs/multitenancy.md,
    # common/tenancy.py). A TENANT sub-world (hvd.create_tenant) gets
    # a nonzero world_id stamped on every control frame and a name
    # labelling its metrics/trace series; weight and quotas feed the
    # process-local QoS scheduler interleaving concurrent tenants'
    # negotiation cycles. The coordinator's weight/quota values are
    # broadcast in the handshake and win over rank-local env (like
    # the fusion threshold), so scheduling state is world-replicated.
    world_id: int = 0      # derived, never read from env
    tenant_name: str = ""  # derived, never read from env
    tenant_weight: float = 1.0
    tenant_quota_bytes_s: float = 0.0   # 0 = unlimited
    tenant_quota_cycles_s: float = 0.0  # 0 = unlimited
    # Service mode (hvdtpurun --service): rank 0 of the default world
    # opens the tenant service gate — jobs attach/detach and pull
    # parameter snapshots over a broadcast fanout without the fleet
    # re-rendezvousing. service_port 0 binds an ephemeral port.
    service_enabled: bool = False
    service_port: int = 0

    # Elastic/launcher-provided identity (reference: test/common.py:25-57
    # reads OMPI_COMM_WORLD_RANK; we read HOROVOD_RANK/SIZE first).
    rank: int = -1
    size: int = -1
    local_rank: int = -1
    local_size: int = -1

    @staticmethod
    def from_env() -> "Config":
        c = Config()
        c.fusion_threshold_bytes = _env_int(
            "HOROVOD_FUSION_THRESHOLD", c.fusion_threshold_bytes)
        c.cycle_time_ms = _env_float("HOROVOD_CYCLE_TIME", c.cycle_time_ms)
        c.cache_enabled = _env_bool("HOROVOD_CACHE_ENABLED",
                                    c.cache_enabled)
        c.cache_capacity = _env_int("HOROVOD_CACHE_CAPACITY",
                                    c.cache_capacity)
        c.cache_speculative = _env_bool("HOROVOD_CACHE_SPECULATIVE",
                                        c.cache_speculative)
        c.zero_copy = _env_bool("HOROVOD_TPU_ZERO_COPY", c.zero_copy)
        c.reactor = _env_bool("HOROVOD_TPU_REACTOR", c.reactor)
        c.zerocopy_send_threshold = _env_int(
            "HOROVOD_TPU_ZEROCOPY_SEND_THRESHOLD",
            c.zerocopy_send_threshold)
        c.ring_threshold_bytes = _env_int(
            "HOROVOD_TPU_RING_THRESHOLD", c.ring_threshold_bytes)
        c.shm_enabled = _env_bool("HOROVOD_TPU_SHM", c.shm_enabled)
        c.compression = os.environ.get("HOROVOD_COMPRESSION",
                                       c.compression).lower()
        # Validate through THE shared name table (wire_dtype.py) —
        # a second hardcoded list here would desync the moment a new
        # wire dtype lands. A typo must not silently run
        # uncompressed: wire_code_of raises naming the knob.
        from horovod_tpu.common import wire_dtype as _wdt
        _wdt.wire_code_of(c.compression)
        c.overlap_buckets = _env_int("HOROVOD_OVERLAP_BUCKETS",
                                     c.overlap_buckets)
        c.overlap_bucket_bytes = _env_int("HOROVOD_OVERLAP_BYTES",
                                          c.overlap_bucket_bytes)
        c.overlap_inflight = _env_int("HOROVOD_OVERLAP_INFLIGHT",
                                      c.overlap_inflight)
        c.overlap_chunk_bytes = _env_int("HOROVOD_OVERLAP_CHUNK_BYTES",
                                         c.overlap_chunk_bytes)
        c.two_level = _env_bool("HOROVOD_TWO_LEVEL", c.two_level)
        c.two_level_threshold_bytes = _env_int(
            "HOROVOD_TWO_LEVEL_THRESHOLD", c.two_level_threshold_bytes)
        c.ici_enabled = _env_bool("HOROVOD_TPU_ICI", c.ici_enabled)
        c.ici_devices = _env_int("HOROVOD_TPU_ICI_DEVICES",
                                 c.ici_devices)
        c.ici_threshold_bytes = _env_int(
            "HOROVOD_TPU_ICI_THRESHOLD", c.ici_threshold_bytes)
        c.idle_backoff_ms = _env_float(
            "HOROVOD_TPU_IDLE_BACKOFF", c.idle_backoff_ms)
        c.hierarchical_allreduce = _env_bool(
            "HOROVOD_HIERARCHICAL_ALLREDUCE", c.hierarchical_allreduce)
        c.hierarchical_allgather = _env_bool(
            "HOROVOD_HIERARCHICAL_ALLGATHER", c.hierarchical_allgather)
        c.hier_controller = _env_bool(
            "HOROVOD_TPU_HIER_CONTROLLER", c.hier_controller)
        c.xla_broadcast = os.environ.get("HOROVOD_XLA_BCAST",
                                         c.xla_broadcast).lower()
        if c.xla_broadcast not in ("psum", "tree"):
            # A typo must not silently pick a rendering — and per-rank
            # divergence would compile different collectives for the
            # same negotiated broadcast and hang the mesh.
            raise ValueError(
                f"HOROVOD_XLA_BCAST={c.xla_broadcast!r}: must be "
                "'psum' or 'tree'")
        c.timeline_path = os.environ.get("HOROVOD_TIMELINE", "")
        c.timeline_mark_cycles = _env_bool(
            "HOROVOD_TIMELINE_MARK_CYCLES", c.timeline_mark_cycles)
        c.trace_path = os.environ.get("HOROVOD_TPU_TRACE", "")
        c.trace_interval_s = _env_float(
            "HOROVOD_TPU_TRACE_INTERVAL", c.trace_interval_s)
        c.metrics_enabled = _env_bool("HOROVOD_TPU_METRICS",
                                      c.metrics_enabled)
        c.metrics_interval_s = _env_float(
            "HOROVOD_TPU_METRICS_INTERVAL", c.metrics_interval_s)
        c.metrics_port = _env_int("HOROVOD_TPU_METRICS_PORT",
                                  c.metrics_port)
        c.metrics_addr = os.environ.get("HOROVOD_TPU_METRICS_ADDR",
                                        c.metrics_addr)
        c.metrics_log = os.environ.get("HOROVOD_TPU_METRICS_LOG",
                                       c.metrics_log)
        c.async_completion = _env_bool(
            "HOROVOD_ASYNC_COMPLETION", c.async_completion)
        c.stall_check_disable = _env_bool(
            "HOROVOD_STALL_CHECK_DISABLE", c.stall_check_disable)
        c.stall_check_time_seconds = _env_float(
            "HOROVOD_STALL_CHECK_TIME_SECONDS", c.stall_check_time_seconds)
        c.stall_shutdown_time_seconds = _env_float(
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
            c.stall_shutdown_time_seconds)
        c.heartbeat_interval_s = _env_float(
            "HOROVOD_HEARTBEAT_INTERVAL", c.heartbeat_interval_s)
        c.heartbeat_timeout_s = _env_float(
            "HOROVOD_HEARTBEAT_TIMEOUT", c.heartbeat_timeout_s)
        c.autotune = _env_bool("HOROVOD_AUTOTUNE", c.autotune)
        c.autotune_log = os.environ.get("HOROVOD_AUTOTUNE_LOG", "")
        c.autotune_warmup_samples = _env_int(
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES", c.autotune_warmup_samples)
        c.autotune_steps_per_sample = _env_int(
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", c.autotune_steps_per_sample)
        c.autotune_bayes_opt_max_samples = _env_int(
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
            c.autotune_bayes_opt_max_samples)
        c.autotune_gaussian_process_noise = _env_float(
            "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
            c.autotune_gaussian_process_noise)
        c.log_level = os.environ.get("HOROVOD_LOG_LEVEL", c.log_level)
        c.log_hide_time = _env_bool("HOROVOD_LOG_HIDE_TIME", c.log_hide_time)
        c.controller_addr = os.environ.get("HOROVOD_CONTROLLER_ADDR", "")
        c.controller_port = _env_int("HOROVOD_CONTROLLER_PORT", 0)
        c.controller_fd = _env_int("HOROVOD_CONTROLLER_FD", c.controller_fd)
        c.secret_key = os.environ.get("HOROVOD_SECRET_KEY", "")
        c.start_timeout = _env_float("HOROVOD_START_TIMEOUT", c.start_timeout)
        c.native_core = _env_bool("HOROVOD_TPU_NATIVE", c.native_core)
        c.elastic_enabled = _env_bool("HOROVOD_ELASTIC",
                                      c.elastic_enabled)
        c.elastic_window_s = _env_float("HOROVOD_ELASTIC_WINDOW",
                                        c.elastic_window_s)
        c.elastic_min_world = _env_int("HOROVOD_ELASTIC_MIN_WORLD",
                                       c.elastic_min_world)
        c.elastic_port = _env_int("HOROVOD_TPU_ELASTIC_PORT",
                                  c.elastic_port)
        c.elastic_join = _env_bool("HOROVOD_ELASTIC_JOIN",
                                   c.elastic_join)
        c.elastic_join_addr = env_str("HOROVOD_ELASTIC_JOIN_ADDR",
                                      c.elastic_join_addr)
        c.elastic_join_port = _env_int("HOROVOD_ELASTIC_JOIN_PORT",
                                       c.elastic_join_port)
        c.tenant_weight = _env_float("HOROVOD_TENANT_WEIGHT",
                                     c.tenant_weight)
        c.tenant_quota_bytes_s = _env_float(
            "HOROVOD_TENANT_QUOTA_BYTES", c.tenant_quota_bytes_s)
        c.tenant_quota_cycles_s = _env_float(
            "HOROVOD_TENANT_QUOTA_CYCLES", c.tenant_quota_cycles_s)
        c.service_enabled = _env_bool("HOROVOD_TPU_SERVICE",
                                      c.service_enabled)
        c.service_port = _env_int("HOROVOD_TPU_SERVICE_PORT",
                                  c.service_port)
        c.rank = _env_int("HOROVOD_RANK", c.rank)
        c.size = _env_int("HOROVOD_SIZE", c.size)
        c.local_rank = _env_int("HOROVOD_LOCAL_RANK", c.local_rank)
        c.local_size = _env_int("HOROVOD_LOCAL_SIZE", c.local_size)
        return c
