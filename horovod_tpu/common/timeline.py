"""Horovod Timeline: Chrome-tracing profile of every collective.

(reference: horovod/common/timeline.{h,cc} — per-tensor state machine
NEGOTIATING → TOP_LEVEL → ACTIVITY, timeline.h:76; rank-0-only file
written by a dedicated thread fed from a lock-free queue,
timeline.h:46-74; enabled by ``HOROVOD_TIMELINE`` with optional cycle
markers via ``HOROVOD_TIMELINE_MARK_CYCLES``, operations.cc:792-798.)

Event vocabulary matches the reference so existing timeline tooling and
the reference's test greps carry over (reference:
test/test_timeline.py:42-58 greps NEGOTIATE_ALLREDUCE / ALLREDUCE /
CYCLE_START): one trace "process" per tensor name, ``NEGOTIATE_<OP>``
spans with per-rank instant ticks, a top-level ``<OP>`` span, nested
activity spans (QUEUE / MEMCPY_IN_FUSION_BUFFER / COLLECTIVE /
MEMCPY_OUT_FUSION_BUFFER), and ``CYCLE_START`` instants.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Dict, Optional

from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck
from horovod_tpu.common.message import RequestType

# Activity names (reference: common.h:30-51 macros).
ACT_QUEUE = "QUEUE"
ACT_MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
ACT_COLLECTIVE = "COLLECTIVE"
ACT_MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"


class _NoOpTimeline:
    """Disabled timeline: every hook is a cheap no-op."""

    enabled = False
    dropped_events = 0

    def attach_drop_counter(self, counter): pass
    def set_world_cycle(self, n): pass
    def negotiate_start(self, name, request_type): pass
    def negotiate_rank_ready(self, name, rank): pass
    def negotiate_end(self, name, verdict=""): pass
    def negotiate_cached(self, fused=False): pass
    def wire_plan(self, detail): pass
    def start(self, name, op_name): pass
    def activity_start_all(self, names, activity): pass
    def activity_end_all(self, names): pass
    def end(self, name): pass
    def async_start(self, name, event_name, batch_id): pass
    def async_end(self, name, event_name, batch_id): pass
    def mark_cycle_start(self): pass
    def shutdown(self): pass


class Timeline(_NoOpTimeline):
    """Enabled timeline writing Chrome-tracing JSON."""

    enabled = True

    # Writer-queue bound: the writer drains to disk on its own thread,
    # and a slow or hung disk previously grew the unbounded queue
    # without limit (every event the job ever traced, resident). Past
    # this depth new events are DROPPED and counted — a lossy trace
    # from a sick disk beats an OOM'd training job.
    DEFAULT_QUEUE_CAPACITY = 1 << 16

    def __init__(self, path: str, mark_cycles: bool = False,
                 queue_capacity: int = DEFAULT_QUEUE_CAPACITY):
        self._path = path
        self.mark_cycles = mark_cycles
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=queue_capacity)
        self.dropped_events = 0
        # hvd_timeline_dropped_events_total mirror (metrics plane);
        # the runtime swaps in a real counter when metrics are on.
        self._drop_metric = None
        self._pids: Dict[str, int] = {}
        self._next_pid = 1
        self._wc = 0  # world cycle number (set_world_cycle)
        self._lock = lockdep.lock("timeline.Timeline._lock")
        self._start_ts = time.monotonic()
        self._writer = threading.Thread(target=self._write_loop,
                                        name="hvd-timeline-writer",
                                        daemon=True)
        self._writer.start()

    def attach_drop_counter(self, counter) -> None:
        self._drop_metric = counter

    def set_world_cycle(self, n: int) -> None:
        """The world-identical negotiation-round sequence number
        (common/trace.py): stamped into every span-opening event's
        args as ``wc`` so per-rank timeline files correlate with the
        merged world trace — and with each other — by eye, without
        the aggregator armed. A bare int store; the runtime updates
        it once per completed world round."""
        self._wc = n

    def _put(self, rec: dict) -> None:
        """Enqueue one event; on overflow drop it and count the drop
        (surfaced in the stall report and the metrics registry). The
        counter bump is racy-cheap on purpose: drops only happen when
        the writer is already wedged."""
        try:
            self._queue.put_nowait(rec)
        except queue.Full:
            self.dropped_events += 1
            if self._drop_metric is not None:
                self._drop_metric.inc()

    # -- writer thread (reference: timeline.h:46-74 TimelineWriter) ------
    def _write_loop(self):
        threadcheck.register_role("hvd-timeline-writer")
        with open(self._path, "w") as f:
            f.write("[\n")
            first = True
            while True:
                rec = self._queue.get()
                if rec is None:
                    break
                if not first:
                    f.write(",\n")
                f.write(json.dumps(rec))
                first = False
                f.flush()
            f.write("\n]\n")

    def _ts(self) -> int:
        return int((time.monotonic() - self._start_ts) * 1e6)

    def _pid(self, name: str) -> int:
        with self._lock:
            pid = self._pids.get(name)
            if pid is None:
                pid = self._next_pid
                self._next_pid += 1
                self._pids[name] = pid
                self._put({"name": "process_name", "ph": "M",
                           "pid": pid, "args": {"name": name}})
                self._put({"name": "process_sort_index", "ph": "M",
                           "pid": pid, "args": {"sort_index": pid}})
            return pid

    # Event phases that OPEN (or fully describe) a span get the world
    # cycle stamp; closing "E"/"e" events inherit it in the viewer, so
    # stamping them too would only bloat the file.
    _WC_PHASES = frozenset(("B", "X", "i", "b"))

    def _emit(self, ph: str, name: str, event_name: str, **kw):
        rec = {"ph": ph, "pid": self._pid(name), "ts": self._ts()}
        if event_name:
            rec["name"] = event_name
        rec.update(kw)
        if ph in self._WC_PHASES:
            rec.setdefault("args", {})["wc"] = self._wc
        self._put(rec)

    # -- negotiation (reference: timeline.cc NegotiateStart/RankReady/End,
    # called from IncrementTensorCount, operations.cc:174-186) -----------
    def negotiate_start(self, name: str, request_type) -> None:
        op = RequestType(request_type).name
        self._emit("B", name, f"NEGOTIATE_{op}")

    def negotiate_rank_ready(self, name: str, rank: int) -> None:
        self._emit("X", name, f"{rank}", dur=0)

    def negotiate_end(self, name: str, verdict: str = "") -> None:
        # ``verdict`` names the resolved wire dtype so the span's end
        # carries the compression decision for this tensor.
        if verdict:
            self._emit("E", name, "", args={"wire": verdict})
        else:
            self._emit("E", name, "")

    def wire_plan(self, detail: str) -> None:
        """Instant marker naming a fused batch's stamped
        (algorithm, wire dtype) — NEGOTIATE_WIRE_PLAN in the trace."""
        self._emit("i", "cycle", f"NEGOTIATE_WIRE_PLAN {detail}",
                   s="g")

    def negotiate_cached(self, fused: bool = False) -> None:
        """Instant marker for a cycle negotiated entirely through the
        response-cache bitmask fast path — no per-tensor NEGOTIATE
        span exists on such cycles, so this is the trace's evidence
        of where negotiation time went (docs/performance.md).
        ``fused`` marks the speculative single-round variant, where
        the broadcast that followed this mark also carried the
        world-reduced data."""
        self._emit("i", "cycle",
                   "NEGOTIATE_CACHED_FUSED" if fused
                   else "NEGOTIATE_CACHED", s="g")

    # -- execution spans -------------------------------------------------
    def start(self, name: str, op_name: str) -> None:
        self._emit("B", name, op_name)

    def activity_start_all(self, names, activity: str) -> None:
        for name in names:
            self._emit("B", name, activity)

    def activity_end_all(self, names) -> None:
        for name in names:
            self._emit("E", name, "")

    def end(self, name: str) -> None:
        self._emit("E", name, "")

    # -- async (deferred-close) spans -----------------------------------
    # Chrome/Perfetto ASYNC NESTABLE events ("b"/"e"), paired by
    # (category, id, name) instead of the per-pid B/E stack. Used for
    # collectives whose spans close at COMPLETION (async backends): a
    # tensor legally re-negotiates the same name while its previous
    # batch is still in flight, and deferred plain-E events would then
    # mispair with the new spans. The id is unique per (batch, TENSOR)
    # — viewers pair async events globally by (cat, id, name), not per
    # pid, so a batch-only id would merge a fused batch's N tensors
    # into one async tree and mispair their spans with each other.
    def _async_id(self, name: str, batch_id: int) -> str:
        return f"{batch_id}.{self._pid(name)}"

    def async_start(self, name: str, event_name: str,
                    batch_id: int) -> None:
        self._emit("b", name, event_name, cat="hvd",
                   id=self._async_id(name, batch_id))

    def async_end(self, name: str, event_name: str,
                  batch_id: int) -> None:
        self._emit("e", name, event_name, cat="hvd",
                   id=self._async_id(name, batch_id))

    def mark_cycle_start(self) -> None:
        if self.mark_cycles:
            self._emit("i", "cycle", "CYCLE_START", s="g")

    def shutdown(self) -> None:
        # A bounded queue can be full when the writer is wedged on a
        # sick disk: give the sentinel a short blocking window, then
        # give up — joining a stuck writer would hang teardown, and
        # the trace is already lossy at that point.
        try:
            self._queue.put(None, timeout=1.0)
        except queue.Full:
            pass
        self._writer.join(timeout=5.0)


def create_timeline(path: str, mark_cycles: bool = False):
    """Rank-0 only, like the reference (timeline.h:78-79)."""
    if not path:
        return _NoOpTimeline()
    return Timeline(path, mark_cycles)


NOOP_TIMELINE = _NoOpTimeline()
