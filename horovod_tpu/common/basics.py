"""Process lifecycle + identity: init / shutdown / rank / size / ...

Equivalent of the reference's ``HorovodBasics`` ctypes surface
(reference: horovod/common/__init__.py:51-154) and the C API behind it
(reference: horovod/common/operations.cc:1371-1426 horovod_init/rank/...).

Identity comes from the launcher's env (``HOROVOD_RANK``/``HOROVOD_SIZE``
+ ``HOROVOD_CONTROLLER_ADDR``/``PORT``, exported by hvdtpurun — see
horovod_tpu/run) the way the reference reads MPI's; with no env set,
``init()`` brings up a size-1 world, which still runs the full cycle
loop so async semantics/fusion/timeline behave identically at any size.

Multi-tenancy (common/tenancy.py, docs/multitenancy.md): one process
may host SEVERAL runtimes at once — the default world built here plus
any tenants created with ``create_tenant``. The module-level ops API
routes through :func:`active_runtime`, which a tenant's ``use()``
scope (a contextvar, so thread- and task-safe) points at its own
runtime; everything else keeps reading the default world.
"""

from __future__ import annotations

import atexit
import contextvars
from typing import Optional

from horovod_tpu.common import lockdep
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network
from horovod_tpu.common.config import Config
from horovod_tpu.common.controller import (
    Controller, LocalController, TcpCoordinator, TcpWorker,
)
from horovod_tpu.common.runtime import Runtime
from horovod_tpu.ops.local_ops import LocalBackend
from horovod_tpu.ops.operation_manager import OperationManager
from horovod_tpu.ops.socket_ops import SocketBackend
from horovod_tpu.ops.xla_ops import XlaMeshBackend

_lock = lockdep.lock("basics._lock")
_runtime: Optional[Runtime] = None

# The runtime the module-level ops API targets in THIS context: a
# tenant scope (tenancy.Tenant.use) sets it; None means the default
# world. A contextvar (not a plain global) so two threads driving two
# tenants never race each other's routing.
_active_runtime: "contextvars.ContextVar[Optional[Runtime]]" = \
    contextvars.ContextVar("horovod_tpu_active_runtime", default=None)


def _require_runtime() -> Runtime:
    if _runtime is None:
        raise ValueError(
            "horovod_tpu has not been initialized; run hvd.init() first.")
    return _runtime


def active_runtime() -> Runtime:
    """The runtime ops should target: the tenant scoped in via
    ``Tenant.use()`` when inside one, the default world otherwise."""
    rt = _active_runtime.get()
    return rt if rt is not None else _require_runtime()


def active_scope() -> str:
    """Auto-name counter scope of the active runtime ('' = default
    world) — per-tenant scoping keeps each tenant's
    ``<op>.noname.<n>`` sequence world-consistent no matter how its
    co-tenants' submissions interleave in this process."""
    rt = _active_runtime.get()
    return rt._tenant if rt is not None else ""


def _is_full_world(ranks, env_size: int) -> bool:
    """True when a comm list names the ENTIRE launched world — that
    sub-world IS the default world and may keep its env endpoint
    (and the launcher's reserved listener fd)."""
    return env_size > 0 and ranks == list(range(env_size))


def _build_runtime(cfg: Config, coordinator_listener=None,
                   elastic_ctx=None) -> Runtime:
    """Construct and start one runtime from a fully-resolved Config:
    controller (with the world id + tenant descriptor in the
    handshake), backends, op manager, autotuner. Shared by init()
    (the default world) and tenancy.create_tenant (tenant worlds —
    several may coexist in one process; nothing here touches module
    globals)."""
    secret = cfg.secret_key.encode() if cfg.secret_key else b""
    size = cfg.size if cfg.size > 0 else 1
    rank = cfg.rank if cfg.rank >= 0 else 0
    # Kernel-side wire knobs (docs/performance.md Layer 6): the
    # MSG_ZEROCOPY send threshold is a channel-layer module hook (it
    # gates sends made during rendezvous too), the reactor switch is
    # stamped on the controller below once it exists. Both are purely
    # rank-local recv/send disciplines — the wire stays byte-identical
    # — so heterogeneous worlds interoperate.
    network.set_zerocopy_threshold(cfg.zerocopy_send_threshold)
    elastic_port = elastic_ctx.port if elastic_ctx is not None \
        and size > 1 else None

    tenant_desc = None
    if cfg.world_id and rank == 0:
        from horovod_tpu.common import tenancy as _tenancy
        tenant_desc = _tenancy.descriptor_of(cfg)

    if size == 1:
        controller: Controller = LocalController()
    elif rank == 0:
        listener = coordinator_listener
        if listener is None and cfg.controller_fd >= 0:
            import socket as _socket
            listener = _socket.socket(fileno=cfg.controller_fd)
        coord = TcpCoordinator(size, port=cfg.controller_port,
                               secret=secret,
                               start_timeout=cfg.start_timeout,
                               listener=listener,
                               hierarchical=cfg.hier_controller,
                               heartbeat_interval=cfg.heartbeat_interval_s,
                               heartbeat_timeout=cfg.heartbeat_timeout_s,
                               elastic_port=elastic_port,
                               world_id=cfg.world_id,
                               tenant_desc=tenant_desc)
        coord.accept_workers()
        controller = coord
    else:
        if not cfg.controller_addr or not cfg.controller_port:
            raise ValueError(
                "HOROVOD_CONTROLLER_ADDR/PORT must be set for "
                "multi-process init (use the hvdtpurun launcher).")
        controller = TcpWorker(rank, size, cfg.controller_addr,
                               cfg.controller_port, secret=secret,
                               start_timeout=cfg.start_timeout,
                               heartbeat_interval=cfg.heartbeat_interval_s,
                               heartbeat_timeout=cfg.heartbeat_timeout_s,
                               elastic_port=elastic_port,
                               world_id=cfg.world_id)
    # Rank-local reactor opt-out (HOROVOD_TPU_REACTOR=0): the batched
    # recv discipline and the chunked-relay legs fall back to the
    # sequential/store-and-forward paths on THIS rank only.
    controller._reactor = cfg.reactor

    # Install the world-identical elastic membership (the
    # coordinator's broadcast endpoint map) for this generation.
    endpoints = getattr(controller, "elastic_endpoints", None)
    if elastic_ctx is not None and endpoints is not None:
        table = dict(endpoints)
        host0, port0 = table[0]
        if not host0:  # the coordinator's own placeholder entry
            table[0] = (cfg.controller_addr or "127.0.0.1", port0)
        elastic_ctx.apply_membership(
            elastic_ctx.membership.generation, controller.rank,
            controller.size, table)

    from horovod_tpu.ops.shm_ops import ShmBackend
    socket_backend = SocketBackend(controller, secret=secret,
                                   config=cfg)
    backends = [
        XlaMeshBackend(controller, config=cfg),
        ShmBackend(controller, fallback=socket_backend, config=cfg,
                   secret=secret),
        socket_backend,
        LocalBackend(lambda: controller.size),
    ]
    op_manager = OperationManager(backends)

    parameter_manager = None
    if cfg.autotune:
        from horovod_tpu.common.parameter_manager import ParameterManager
        parameter_manager = ParameterManager(cfg, controller)

    rt = Runtime(cfg, controller, op_manager, parameter_manager)
    rt.start()
    return rt


def init(comm=None, config: Optional[Config] = None,
         coordinator_listener=None) -> None:
    """Initialize the runtime. ``comm`` accepts either a (rank, size)
    TUPLE for explicit worlds, or a LIST of global ranks forming a
    sub-world (reference: common/__init__.py:58-84 init(comm=ranks)):
    members are renumbered 0..len-1 in list order, the first listed
    rank's process hosts the sub-world's coordinator on a port derived
    from the membership, and processes NOT in the list come up as
    size-1 worlds so they can keep doing local work while the subset
    runs collectives. With ``comm=None`` identity comes from the
    environment. (For CONCURRENT sub-worlds with QoS scheduling and
    per-tenant observability, use ``hvd.create_tenant`` —
    docs/multitenancy.md.)

    ``coordinator_listener`` (rank 0 only) — an already-bound listening
    socket for the coordinator to adopt, closing the reserve/release/
    rebind race in launch layers that must publish the port before
    init. Launcher-spawned rank 0 can instead inherit the reservation
    as a file descriptor via ``HOROVOD_CONTROLLER_FD``.
    """
    global _runtime
    with _lock:
        if _runtime is not None and _runtime.alive:
            return  # already initialized (reference: InitializeHorovodOnce
                    # test-and-set, operations.cc:1342-1360)
        cfg = config or Config.from_env()
        hlog.set_level(cfg.log_level)
        # Publish the wire-compression latch (common/wire_dtype.py):
        # the framework-level Compression helpers become pass-throughs
        # while the negotiated data plane compresses, so gradients are
        # never cast twice.
        from horovod_tpu.common import wire_dtype as _wd
        _wd.set_active(_wd.wire_code_of(cfg.compression))
        if isinstance(comm, list):
            ranks = [int(r) for r in comm]
            env_size = cfg.size
            g_rank = cfg.rank if cfg.rank >= 0 else 0
            full_world = _is_full_world(ranks, env_size)
            # An inherited coordinator fd (launcher-reserved) serves
            # the FULL world's published endpoint; it is only valid
            # when this process leads that full world. Close it
            # otherwise or it lingers as a dead listener that eats the
            # port and black-holes connects.
            if cfg.controller_fd >= 0 and not (full_world
                                               and g_rank == 0):
                import os as _os
                try:
                    _os.close(cfg.controller_fd)
                except OSError:
                    pass
                cfg.controller_fd = -1
            if g_rank in ranks:
                cfg.rank = ranks.index(g_rank)
                cfg.size = len(ranks)
                if not full_world and cfg.controller_port:
                    # The env endpoint belongs to the full world:
                    # derive a per-membership port (tenancy.py) so a
                    # sub-coordinator never collides with the full
                    # world's listener OR another sub-world's — the
                    # old first-rank-only derivation collided for two
                    # subsets sharing a first rank, and a subset
                    # anchored at global rank 0 squatted the fleet
                    # port itself. Every member derives identically
                    # from the full list; the world id below turns
                    # any residual collision into a named handshake
                    # error. On multi-host launches where the first
                    # listed rank is not on the env-addr host, set
                    # HOROVOD_CONTROLLER_ADDR to that rank's host
                    # before calling init.
                    from horovod_tpu.common import tenancy as _tenancy
                    cfg.controller_port = _tenancy.derive_subworld_port(
                        cfg.controller_port, "", ranks)
                    cfg.world_id = _tenancy.derive_world_id("", ranks)
            else:
                cfg.rank, cfg.size = 0, 1
        elif comm is not None:
            rank, size = comm
            cfg.rank, cfg.size = int(rank), int(size)
        secret = cfg.secret_key.encode() if cfg.secret_key else b""

        # Elastic worlds (HOROVOD_ELASTIC=1, common/elastic.py): bind
        # this process's re-rendezvous listener once; a respawned
        # joiner (HOROVOD_ELASTIC_JOIN=1) instead dials the advertised
        # coordinator endpoint and blocks until the next rendezvous
        # barrier admits it with a fresh dense rank.
        elastic_ctx = None
        if cfg.elastic_enabled and not isinstance(comm, list):
            from horovod_tpu.common import elastic as _elastic
            if cfg.elastic_join:
                assignment = _elastic.join_world(cfg, secret)
                cfg.rank = assignment.rank
                cfg.size = assignment.size
                cfg.controller_addr = assignment.controller_addr
                cfg.controller_port = assignment.controller_port
                cfg.controller_fd = -1
            if cfg.size > 1 or cfg.size <= 0:
                elastic_ctx = _elastic.ensure_context(cfg, secret)

        rt = _build_runtime(cfg,
                            coordinator_listener=coordinator_listener,
                            elastic_ctx=elastic_ctx)
        _runtime = rt
        from horovod_tpu import ops
        ops.reset_name_counters("")
        # Service mode (docs/multitenancy.md): rank 0 of a --service
        # fleet opens the tenant gate so jobs can attach/detach and
        # pull parameter snapshots without the fleet re-rendezvousing.
        if cfg.service_enabled and not cfg.world_id \
                and rt.controller.rank == 0:
            from horovod_tpu.common import tenancy as _tenancy
            _tenancy.start_service_gate(cfg, secret)
        hlog.debug(f"horovod_tpu initialized: rank {rt.controller.rank}"
                   f" of {rt.controller.size}", rank=rt.controller.rank)


def shutdown() -> None:
    """Stop the background loop; pending handles complete with
    SHUT_DOWN_ERROR (reference: operations.cc:1377-1383 horovod_shutdown,
    898-913)."""
    global _runtime
    with _lock:
        rt = _runtime
        if rt is None:
            return
        rt.request_shutdown()
        rt.join(timeout=30.0)
        _runtime = None
        from horovod_tpu.common import wire_dtype as _wd
        _wd.set_active(_wd.WIRE_NONE)
    from horovod_tpu.common import tenancy as _tenancy
    _tenancy.stop_service_gate()


atexit.register(shutdown)


def initialized() -> bool:
    return _runtime is not None and _runtime.alive


def runtime() -> Runtime:
    """Internal: the live Runtime (framework adapters use this)."""
    return _require_runtime()


def rank() -> int:
    return active_runtime().controller.topology.rank


def size() -> int:
    return active_runtime().controller.topology.size


def local_rank() -> int:
    return active_runtime().controller.topology.local_rank


def local_size() -> int:
    return active_runtime().controller.topology.local_size


def cross_rank() -> int:
    """Rank among hosts (reference: global_state.h cross_rank)."""
    return active_runtime().controller.topology.cross_rank


def cross_size() -> int:
    return active_runtime().controller.topology.cross_size


def is_homogeneous() -> bool:
    """True when every host runs the same number of ranks
    (reference: operations.cc:741-757)."""
    return active_runtime().controller.topology.is_homogeneous


def metrics() -> dict:
    """The live metrics view (HOROVOD_TPU_METRICS=1, docs/metrics.md):
    ``{"enabled": bool, "local": {...}, "world": {...}|None,
    "http_port": int|None}``. ``local`` is this rank's freshest
    registry snapshot; ``world`` is the control-tree aggregate and
    materializes only on rank 0 (the fold point); ``http_port`` is the
    live Prometheus endpoint's bound port when
    HOROVOD_TPU_METRICS_PORT enabled it. With metrics disabled the
    snapshots are empty and ``enabled`` is False. Inside a tenant
    scope this is the TENANT's view, with every series carrying its
    ``tenant`` label."""
    return active_runtime().metrics_view()


def coordinator_threads_supported() -> bool:
    """Enqueues may come from any thread (the table is mutex-guarded),
    so multi-threaded use is always supported — unlike the reference,
    where this depends on MPI_THREAD_MULTIPLE
    (reference: operations.cc:674-693, common/__init__.py:150-154)."""
    return True


def mpi_threads_supported() -> bool:
    """Reference-compat alias for coordinator_threads_supported."""
    return coordinator_threads_supported()
