"""World trace plane: clock-aligned cross-rank tracing, per-cycle
straggler attribution, and the crash flight recorder.

Every diagnostic surface this framework had before this module was
rank-local: the rank-0 timeline profiles one process, the stall
inspector reports one coordinator's table, the metrics plane sums
counters but keeps no event order. At scale the questions that matter
are cross-rank and clock-aligned — *which rank makes every cycle
slow*, and *what was the world doing in the seconds before it died?*
Four coupled pieces answer them:

* :class:`ClockSync` — NTP-style per-peer clock offset estimation
  piggybacked on existing control traffic: the coordinator's PING
  beacon supplies the (t1) send stamp, the worker's next TRACE frame
  echoes (t2, t3), and the frame's arrival supplies (t4). Offsets are
  smoothed by a minimum-RTT filter (congested samples are
  symmetric-delay violations and get discarded), maintained ON RANK 0
  — the coordinator clock is the world's reference frame.
* :class:`TraceCollector` / :class:`WorldTraceWriter` — every rank
  batches completed spans (bounded, drop-counted) into TAG_TRACE
  frames that ride the control tree out-of-band like METRICS frames;
  rank 0 writes ONE Chrome-trace (catapult) file with a track per
  rank, span timestamps corrected into the coordinator clock, and the
  world-identical cycle sequence number on every span
  (``HOROVOD_TPU_TRACE``, ``hvdtpurun --trace``).
* :class:`StragglerTracker` — the coordinator stamps per-rank arrival
  times at every negotiation gather (native paths included:
  ``hvd_gather_frames``/``hvd_steady_coord`` return per-peer
  CLOCK_MONOTONIC stamps) and attributes each cycle's critical path:
  ``hvd_cycle_skew_seconds``, per-rank arrival-lag max-gauges and a
  last-arriver counter per rank on the metrics plane, plus the
  stall-report line ("rank 3 last-arriver in 84% of the last 1000
  gathers").
* :class:`FlightRecorder` — a lock-cheap fixed-size ring of recent
  cycle/abort/elastic events per rank, ON BY DEFAULT (compiled-out
  no-op writes when ``HOROVOD_TPU_FLIGHT=0``, the NOOP_METRIC
  pattern), dumped to a postmortem JSONL on ``WorldAbortedError``,
  stall shutdown and SIGUSR2 — a production abort ships the last N
  seconds of world history with no profiling armed.

The recorder and the clock table are process-lifetime singletons (the
lockdep pattern): they must survive elastic re-inits so a postmortem
spans world generations, and modules without a Runtime in hand
(common/elastic.py, common/faults.py) can still record.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck
from horovod_tpu.common import logging as hlog
from horovod_tpu.common.wire import (
    EV_ABORT, EV_CYCLE, EV_ELASTIC, EV_FAULT, EV_MARK, EV_NAMES,
    EV_SELFOP, EV_STALL, EV_TEARDOWN, SPAN_MARK, SPAN_SLICE,
    combine_trace_frames, parse_trace_frame, serialize_trace_frame,
)

__all__ = [
    "EV_CYCLE", "EV_ABORT", "EV_ELASTIC", "EV_STALL", "EV_FAULT",
    "EV_TEARDOWN", "EV_MARK", "EV_SELFOP", "ClockSync", "TraceCollector",
    "NOOP_TRACE", "FlightRecorder", "NOOP_RECORDER", "flight",
    "clock", "StragglerTracker", "WorldTraceWriter",
    "install_sigusr2", "serialize_trace_frame", "parse_trace_frame",
    "combine_trace_frames",
]


# ---------------------------------------------------------------------------
# Clock alignment
# ---------------------------------------------------------------------------

class _PeerClock:
    """Smoothed offset estimate for one peer: keep the recent samples
    and trust the one with the smallest round trip — queueing delay is
    the symmetric-delay violation that skews NTP math, and it only
    ever INFLATES the RTT, so min-RTT is the classic filter."""

    __slots__ = ("samples",)
    WINDOW = 32

    def __init__(self):
        self.samples: deque = deque(maxlen=self.WINDOW)

    def add(self, offset: float, rtt: float) -> None:
        self.samples.append((rtt, offset))

    def estimate(self) -> Optional[Tuple[float, float]]:
        """(offset_seconds, rtt_seconds) of the best recent sample, or
        None before any sample arrived. Offset is peer_clock minus
        coordinator_clock: coordinator time = peer time - offset."""
        if not self.samples:
            return None
        rtt, offset = min(self.samples)
        return offset, rtt


class ClockSync:
    """Both halves of the piggybacked clock exchange.

    Coordinator side: :meth:`ping_sent` records (seq -> t1) for every
    PING the beacon fans out; :meth:`echo` closes the loop when a
    worker's TRACE frame answers with (t2, t3) and the frame arrival
    supplies t4:

        rtt    = (t4 - t1) - (t3 - t2)
        offset = ((t2 - t1) + (t3 - t4)) / 2     # peer - coordinator

    Worker side: :meth:`ping_received` notes the latest coordinator
    PING (sender rank 0 only — local-root beacons carry their own
    clocks); :meth:`take_echo` hands the pending answer to the next
    TRACE frame build, consuming it so one ping is answered once.

    Thread-safety: pings arrive on the background loop, echoes are
    consumed there too, but rank 0's table is read from the stall
    report and the HTTP metrics thread — one small lock covers it.
    """

    PING_MEMORY = 128

    def __init__(self):
        self._lock = lockdep.lock("trace.ClockSync._lock")
        self._pings: "OrderedDict[int, float]" = OrderedDict()
        self._peers: Dict[int, _PeerClock] = {}
        self._pending_echo: Optional[Tuple[int, float]] = None

    def reset(self) -> None:
        """Forget every peer and outstanding ping. Elastic resizes
        renumber the survivors densely (common/elastic.py), so a
        per-rank offset table carried across generations would bind
        one host's clock skew to a DIFFERENT host's new rank —
        membership install calls this."""
        with self._lock:
            self._pings.clear()
            self._peers.clear()
            self._pending_echo = None

    # -- coordinator side ------------------------------------------------
    def ping_sent(self, seq: int, t1: float) -> None:
        with self._lock:
            self._pings[seq] = t1
            while len(self._pings) > self.PING_MEMORY:
                self._pings.popitem(last=False)

    def echo(self, rank: int, seq: int, t2: float, t3: float,
             t4: float) -> None:
        with self._lock:
            t1 = self._pings.get(seq)
            if t1 is None:
                return  # answer to a ping we forgot: drop
            rtt = (t4 - t1) - (t3 - t2)
            if rtt < 0:
                return  # clocks moved mid-sample (suspend?): garbage
            offset = ((t2 - t1) + (t3 - t4)) / 2.0
            peer = self._peers.get(rank)
            if peer is None:
                peer = self._peers[rank] = _PeerClock()
            peer.add(offset, rtt)

    def offsets(self) -> Dict[int, Tuple[float, float]]:
        """{rank: (offset_s, rtt_s)} for every peer with samples."""
        with self._lock:
            out = {}
            for r, peer in self._peers.items():
                est = peer.estimate()
                if est is not None:
                    out[r] = est
            return out

    def offset_of(self, rank: int) -> float:
        """Best offset for ``rank`` (0.0 = coordinator itself, or no
        sample yet — spans then align uncorrected, which is exactly
        the pre-PR behavior)."""
        if rank == 0:
            return 0.0
        with self._lock:
            peer = self._peers.get(rank)
        if peer is None:
            return 0.0
        est = peer.estimate()
        return est[0] if est is not None else 0.0

    # -- worker side -----------------------------------------------------
    def ping_received(self, sender_rank: int, seq: int,
                      t2: float) -> None:
        if sender_rank != 0:
            return  # only the coordinator clock is the reference
        with self._lock:
            self._pending_echo = (seq, t2)

    def take_echo(self) -> Optional[Tuple[int, float, float]]:
        with self._lock:
            pending = self._pending_echo
            self._pending_echo = None
        if pending is None:
            return None
        seq, t2 = pending
        return (seq, t2, time.monotonic())


_CLOCK: Optional[ClockSync] = None
_CLOCK_LOCK = lockdep.lock("trace._CLOCK_LOCK")


def clock() -> ClockSync:
    """The process-wide clock table (survives elastic re-inits — the
    offsets of a stable host stay useful across generations)."""
    global _CLOCK
    if _CLOCK is None:
        with _CLOCK_LOCK:
            if _CLOCK is None:
                _CLOCK = ClockSync()
    return _CLOCK


# ---------------------------------------------------------------------------
# Span collection (per rank)
# ---------------------------------------------------------------------------

class _NoOpTraceCollector:
    """Disabled collector: every hook is a cheap no-op, one shared
    instance so the disabled-path test can assert identity."""

    enabled = False
    dropped = 0

    def slice(self, name, ts, dur, cycle): pass
    def mark(self, name, ts, cycle): pass
    def drain(self): return [], 0


NOOP_TRACE = _NoOpTraceCollector()


class TraceCollector(_NoOpTraceCollector):
    """Bounded per-rank span buffer feeding TAG_TRACE frames. Appends
    are a lock + list append; past capacity new spans are DROPPED and
    counted (the drop count rides the next frame's section header) —
    a wedged control plane must never grow an unbounded span list."""

    enabled = True
    CAPACITY = 4096

    def __init__(self, capacity: int = CAPACITY, tenant: str = ""):
        self._lock = lockdep.lock("trace.TraceCollector._lock")
        self._capacity = capacity
        self._spans: List[tuple] = []
        # Tenant sub-worlds (common/tenancy.py) prefix every span name
        # with their tenant id so the merged world trace attributes
        # each round to its job ("jobA:ROUND" vs "ROUND").
        self._prefix = f"{tenant}:" if tenant else ""
        self.dropped = 0

    def _push(self, span: tuple) -> None:
        with self._lock:
            if len(self._spans) >= self._capacity:
                self.dropped += 1
                return
            self._spans.append(span)

    def slice(self, name: str, ts: float, dur: float,
              cycle: int) -> None:
        self._push((SPAN_SLICE, cycle, ts, dur, self._prefix + name))

    def mark(self, name: str, ts: float, cycle: int) -> None:
        self._push((SPAN_MARK, cycle, ts, 0.0, self._prefix + name))

    def drain(self):
        """-> (spans, dropped_since_last_drain)."""
        with self._lock:
            spans, self._spans = self._spans, []
            dropped, self.dropped = self.dropped, 0
        return spans, dropped


def create_collector(enabled: bool, tenant: str = ""):
    return TraceCollector(tenant=tenant) if enabled else NOOP_TRACE


# ---------------------------------------------------------------------------
# Flight recorder (per rank, on by default)
# ---------------------------------------------------------------------------

class _NoOpRecorder:
    """Disabled recorder: record() is a no-op; dump() writes nothing.
    One shared instance (NOOP_RECORDER) so every instrumented write
    site is enumerable by identity in tests, like NOOP_METRIC."""

    enabled = False

    def record(self, ev, cycle=0, arg=None, note=""): pass
    def set_identity(self, rank): pass
    def note_world(self, world_id, tenant, rank): pass
    def events(self): return []
    def dump(self, cause="", origin=-1, path=None): return None


NOOP_RECORDER = _NoOpRecorder()


class FlightRecorder(_NoOpRecorder):
    """Fixed-size ring of recent world events. A write is one clock
    read + a lock + a slot store — cheap enough to stay on by default
    at one event per negotiation round. The ring never allocates
    after construction beyond the stored tuples themselves."""

    enabled = True

    def __init__(self, capacity: int = 512):
        self._lock = lockdep.lock("trace.FlightRecorder._lock")
        self._ring: List[Optional[tuple]] = [None] * max(8, capacity)
        self._next = 0
        self._rank = hconfig.env_int("HOROVOD_RANK", -1)
        # Tenant sub-worlds this process is a member of (tenancy.py):
        # world id -> {"tenant", "rank"}, carried in every dump header
        # so a postmortem can attribute events to jobs.
        self._worlds: dict = {}
        self._dumped = 0

    def set_identity(self, rank: int) -> None:
        """Current-world rank for dump headers (the LAUNCH identity
        from HOROVOD_RANK stays in the filename — stable across
        elastic renumbering)."""
        self._rank = rank

    def note_world(self, world_id: int, tenant: str,
                   rank: int) -> None:
        """Register a tenant sub-world this process joined (the
        default world keeps set_identity); the recorder is process-
        lifetime, so the header names every world it ever served."""
        with self._lock:
            self._worlds[f"{world_id:#010x}"] = {
                "tenant": tenant, "rank": rank}

    def record(self, ev: int, cycle: int = 0,
               arg: Optional[int] = None, note: str = "") -> None:
        entry = (time.monotonic(), ev, cycle, arg, note)
        with self._lock:
            self._ring[self._next % len(self._ring)] = entry
            self._next += 1

    def events(self) -> List[tuple]:
        """Chronological snapshot of the ring. The lock is acquired
        best-effort: ``dump()`` runs inside the SIGUSR2 handler, which
        Python delivers ON the main thread — if that thread is mid-
        ``record()`` and already holds the lock, blocking here would
        wedge the very process the signal is trying to postmortem. A
        torn read of one in-flight slot is an acceptable last resort."""
        got = self._lock.acquire(timeout=0.2)
        try:
            n = len(self._ring)
            start = self._next
            out = [self._ring[(start + i) % n] for i in range(n)]
        finally:
            if got:
                self._lock.release()
        return [e for e in out if e is not None]

    def dump(self, cause: str = "", origin: int = -1,
             path: Optional[str] = None) -> Optional[str]:
        """Append one postmortem block (header line + event lines) to
        the rank's flight file; returns the path. Never raises — this
        runs on abort/signal paths where nothing may be assumed."""
        try:
            if path is None:
                base = hconfig.env_str("HOROVOD_TPU_FLIGHT_DIR", ".")
                launch_rank = hconfig.env_int("HOROVOD_RANK",
                                              self._rank)
                path = os.path.join(
                    base, f"hvd-flight-rank{max(launch_rank, 0)}"
                          f".pid{os.getpid()}.jsonl")
            events = self.events()
            now_wall, now_mono = time.time(), time.monotonic()
            header = {
                "flight": 1, "ts": now_wall, "mono": now_mono,
                "rank": self._rank,
                "launch_rank": hconfig.env_int("HOROVOD_RANK", -1),
                "pid": os.getpid(), "cause": cause, "origin": origin,
                "events": len(events), "dump": self._dumped,
            }
            if self._worlds:
                header["worlds"] = dict(self._worlds)
            try:
                from horovod_tpu.common import elastic as _elastic
                header["generation"] = _elastic.generation()
            except Exception:
                pass
            try:
                header["build"] = build_info()
            except Exception:
                pass
            with open(path, "a") as f:
                f.write(json.dumps(header, separators=(",", ":"))
                        + "\n")
                for t, ev, cyc, arg, note in events:
                    rec = {"t": round(t, 6),
                           "ev": EV_NAMES.get(ev, ev), "cycle": cyc}
                    if arg is not None:
                        # `is not None`, not truthiness: rank 0 as an
                        # abort origin (and generation 0) are real args
                        rec["arg"] = arg
                    if note:
                        rec["note"] = note
                    f.write(json.dumps(rec, separators=(",", ":"))
                            + "\n")
            self._dumped += 1
            return path
        except Exception:
            return None


_FLIGHT = None
_FLIGHT_LOCK = lockdep.lock("trace._FLIGHT_LOCK")


def flight():
    """The process-wide flight recorder. Enabled by default; set
    ``HOROVOD_TPU_FLIGHT=0`` for the compiled-out no-op (every write
    site then holds/calls the shared NOOP_RECORDER). Capacity:
    ``HOROVOD_TPU_FLIGHT_EVENTS`` (default 512). Deliberately not a
    Config field — the recorder must exist before any Config snapshot
    does and survive elastic re-inits (the lockdep pattern)."""
    global _FLIGHT
    if _FLIGHT is None:
        with _FLIGHT_LOCK:
            if _FLIGHT is None:
                if hconfig.env_bool("HOROVOD_TPU_FLIGHT", True):
                    _FLIGHT = FlightRecorder(hconfig.env_int(
                        "HOROVOD_TPU_FLIGHT_EVENTS", 512))
                else:
                    _FLIGHT = NOOP_RECORDER
    return _FLIGHT


def _reset_for_tests() -> None:
    """Drop the singletons so a test can re-read the env."""
    global _FLIGHT, _CLOCK
    with _FLIGHT_LOCK:
        _FLIGHT = None
    with _CLOCK_LOCK:
        _CLOCK = None


_SIGUSR2_INSTALLED = False


def install_sigusr2() -> bool:
    """Dump the flight ring on SIGUSR2 — the live-postmortem poke for
    a job that looks wedged but has not aborted. Main-thread only
    (signal module contract); installation failure is non-fatal."""
    global _SIGUSR2_INSTALLED
    if _SIGUSR2_INSTALLED:
        return True
    try:
        def _handler(signum, frame):
            flight().dump(cause="SIGUSR2")
        signal.signal(signal.SIGUSR2, _handler)
        _SIGUSR2_INSTALLED = True
        return True
    except (ValueError, OSError, AttributeError):
        return False  # non-main thread / unsupported platform


# ---------------------------------------------------------------------------
# Build identity (the hvd_build_info satellite)
# ---------------------------------------------------------------------------

def _native_build_hash() -> str:
    try:
        import hashlib

        from horovod_tpu import native as _native
        so = getattr(_native, "_SO_PATH", None)
        if not so or not os.path.exists(so):
            return "none"
        h = hashlib.sha256()
        with open(so, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()[:12]
    except Exception:
        return "unknown"


def knobs_digest() -> str:
    """Short digest over every armed HOROVOD* knob (name=value,
    sorted) — two dumps with the same digest ran the same config."""
    import hashlib
    items = sorted(f"{k}={v}" for k, v in os.environ.items()
                   if k.startswith("HOROVOD"))
    return hashlib.sha256("\n".join(items).encode()).hexdigest()[:12]


def build_info() -> Dict[str, str]:
    """{version, native .so hash, armed-knobs digest, kernel-feature
    flags} — the identity postmortems need to tell WHICH build
    produced a dump. ``flags`` decodes hvd_build_flags: bit0 io_uring
    compiled in (Makefile probe), bit1 io_uring usable at runtime,
    bit2 MSG_ZEROCOPY compiled in; "none" for a pre-reactor .so."""
    from horovod_tpu import __version__
    from horovod_tpu import native as _native
    f = _native.build_flags()
    names = [name for bit, name in
             ((1, "io_uring"), (2, "io_uring_rt"), (4, "zerocopy"))
             if f & bit]
    return {"version": __version__,
            "native": _native_build_hash(),
            "knobs": knobs_digest(),
            "flags": "+".join(names) if names else "none"}


# ---------------------------------------------------------------------------
# Straggler attribution (rank 0)
# ---------------------------------------------------------------------------

class StragglerTracker:
    """Per-cycle critical-path attribution from the coordinator's
    gather arrival stamps. ``note_gather`` runs on the background
    loop once per negotiation gather (only when the metrics or trace
    plane armed it); the report line and metric mirrors are read from
    other threads, so the window state sits under a small lock."""

    WINDOW = 1000

    def __init__(self, registry=None):
        from horovod_tpu.common import metrics as hmetrics
        reg = registry if registry is not None \
            else hmetrics.NOOP_REGISTRY
        self._reg = reg
        self._lock = lockdep.lock("trace.StragglerTracker._lock")
        self._window: deque = deque(maxlen=self.WINDOW)
        self._last_counts: Dict[int, int] = {}
        self._max_lag: Dict[int, float] = {}
        self._gathers = 0
        self._m_skew = reg.histogram(
            "hvd_cycle_skew_seconds",
            "per negotiation gather: last arrival minus first "
            "arrival (the cycle's straggler-induced critical path)",
            buckets=hmetrics.LATENCY_BUCKETS)
        self._m_lag: Dict[int, object] = {}
        self._m_last: Dict[int, object] = {}

    def _peer_metrics(self, r: int):
        lag = self._m_lag.get(r)
        if lag is None:
            from horovod_tpu.common import metrics as hmetrics
            lag = self._reg.gauge(
                f'hvd_arrival_lag_seconds{{peer="{r}"}}',
                "worst observed gather arrival lag of this peer "
                "behind the cycle's first arrival",
                agg=hmetrics.AGG_MAX)
            self._m_lag[r] = lag
            self._m_last[r] = self._reg.counter(
                f'hvd_last_arriver_total{{peer="{r}"}}',
                "negotiation gathers this peer arrived LAST in")
        return lag, self._m_last[r]

    def note_gather(self, arrivals: Dict[int, float]) -> None:
        """``arrivals``: rank -> coordinator-monotonic stamp of that
        rank's request frame completing. Under the hierarchical
        control plane the ranks are channel OWNERS (a local root
        answers for its host)."""
        if len(arrivals) < 1:
            return
        first = min(arrivals.values())
        last_rank, last_t = max(arrivals.items(),
                                key=lambda kv: (kv[1], kv[0]))
        skew = last_t - first
        self._m_skew.observe(skew)
        with self._lock:
            self._gathers += 1
            old = None
            if len(self._window) == self._window.maxlen:
                old = self._window[0]
            self._window.append(last_rank)
            self._last_counts[last_rank] = \
                self._last_counts.get(last_rank, 0) + 1
            if old is not None:
                self._last_counts[old] -= 1
            for r, t in arrivals.items():
                lag = t - first
                if lag > self._max_lag.get(r, -1.0):
                    self._max_lag[r] = lag
                    gauge, _ = self._peer_metrics(r)
                    gauge.set(lag)
        _, counter = self._peer_metrics(last_rank)
        counter.inc()

    def window_stats(self) -> Dict[str, object]:
        """Snapshot of the attribution window for the supervision
        policy (common/selfop.py): gather count, window occupancy,
        per-rank last-arriver counts and worst lags."""
        with self._lock:
            return {
                "window": len(self._window),
                "gathers": self._gathers,
                "last_counts": dict(self._last_counts),
                "max_lag": dict(self._max_lag),
            }

    def report_line(self) -> str:
        """'rank 3 last-arriver in 84% of the last 1000 gathers
        (max lag 120.0ms)' — worst offenders first, empty before any
        gather was stamped."""
        with self._lock:
            n = len(self._window)
            if n == 0:
                return ""
            worst = sorted(
                ((c, r) for r, c in self._last_counts.items() if c > 0),
                reverse=True)[:3]
            parts = []
            for c, r in worst:
                lag = self._max_lag.get(r, 0.0)
                parts.append(f"rank {r} last-arriver in "
                             f"{100.0 * c / n:.0f}% of the last "
                             f"{n} gathers (max lag "
                             f"{lag * 1000.0:.1f}ms)")
        return "; ".join(parts)


# ---------------------------------------------------------------------------
# The merged world trace (rank 0)
# ---------------------------------------------------------------------------

class WorldTraceWriter:
    """Rank 0's fold point for TAG_TRACE frames: one Chrome-trace
    (catapult) JSON file with a track ("process") per rank, span
    timestamps corrected into the coordinator clock via the
    ClockSync offset table, and the world cycle number in every
    span's args. Writer thread + bounded queue, exactly the Timeline
    discipline — a sick disk drops spans, never blocks the control
    plane."""

    QUEUE_CAPACITY = 1 << 16

    def __init__(self, path: str, clock_sync: Optional[ClockSync] = None):
        self._path = path
        self._clock = clock_sync if clock_sync is not None else clock()
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=self.QUEUE_CAPACITY)
        self._lock = lockdep.lock("trace.WorldTraceWriter._lock")
        self._t0 = time.monotonic()
        self._seen_ranks: set = set()
        self._last_ts: Dict[int, float] = {}
        self.dropped_events = 0
        self.spans_written = 0
        self._writer = threading.Thread(target=self._write_loop,
                                        name="hvd-worldtrace-writer",
                                        daemon=True)
        self._writer.start()

    def _put(self, rec: dict) -> None:
        try:
            self._queue.put_nowait(rec)
        except queue.Full:
            self.dropped_events += 1

    def _write_loop(self):
        threadcheck.register_role("hvd-worldtrace-writer")
        with open(self._path, "w") as f:
            f.write("[\n")
            first = True
            while True:
                rec = self._queue.get()
                if rec is None:
                    break
                if not first:
                    f.write(",\n")
                f.write(json.dumps(rec))
                first = False
                f.flush()
            f.write("\n]\n")

    def _ensure_rank(self, rank: int) -> None:
        if rank in self._seen_ranks:
            return
        self._seen_ranks.add(rank)
        self._put({"name": "process_name", "ph": "M", "pid": rank,
                   "args": {"name": f"rank {rank}"}})
        self._put({"name": "process_sort_index", "ph": "M",
                   "pid": rank, "args": {"sort_index": rank}})

    def add_section(self, rank: int, spans, dropped: int = 0) -> None:
        """Write one rank's span batch, offset-corrected. The offset
        is sampled ONCE per batch and each track is clamped monotonic
        — a drifting estimate between batches must never make a
        rank's own track run backwards in the viewer."""
        if not spans and not dropped:
            return
        offset = self._clock.offset_of(rank)
        with self._lock:
            self._ensure_rank(rank)
            last = self._last_ts.get(rank, float("-inf"))
            for kind, cycle, ts, dur, name in spans:
                t = ts - offset - self._t0
                if t < last:
                    t = last
                last = max(last, t + max(dur, 0.0))
                rec = {"pid": rank, "tid": 0, "name": name,
                       "ts": int(t * 1e6),
                       "args": {"wc": cycle}}
                if kind == SPAN_MARK:
                    rec["ph"] = "i"
                    rec["s"] = "t"
                else:
                    rec["ph"] = "X"
                    rec["dur"] = int(max(dur, 0.0) * 1e6)
                self._put(rec)
                self.spans_written += 1
            if dropped:
                self._put({"pid": rank, "tid": 0, "ph": "i", "s": "t",
                           "name": f"TRACE_DROPPED {dropped}",
                           "ts": int(max(last, 0.0) * 1e6),
                           "args": {"dropped": dropped}})
            self._last_ts[rank] = last

    def ingest(self, owner_rank: int, payload: bytes) -> None:
        """A TAG_TRACE frame off the control tree (any thread that
        recvs control frames). Closes each section's clock-echo loop
        with THIS arrival stamp (t4), then writes its spans. A
        garbled frame is dropped — best-effort, like metrics."""
        t4 = time.monotonic()
        try:
            sections = parse_trace_frame(payload)
        except Exception:
            return
        for sec in sections:
            echo = sec.get("echo")
            if echo is not None:
                seq, t2, t3 = echo
                self._clock.echo(sec["rank"], seq, t2, t3, t4)
            self.add_section(sec["rank"], sec["spans"],
                             sec.get("dropped", 0))

    def close(self) -> None:
        try:
            self._queue.put(None, timeout=1.0)
        except queue.Full:
            pass
        self._writer.join(timeout=5.0)


def clock_offsets_line() -> str:
    """Human line for the stall report: per-peer offset estimates vs
    the coordinator clock ('rank 1 +0.8ms (rtt 0.3ms), ...'), empty
    before any echo closed."""
    offs = clock().offsets()
    if not offs:
        return ""
    parts = [f"rank {r} {o * 1000.0:+.1f}ms (rtt {rtt * 1000.0:.1f}ms)"
             for r, (o, rtt) in sorted(offs.items())]
    return ", ".join(parts)
# -- thread-affinity sanitizer (HOROVOD_TPU_THREADCHECK) ------------------
# No fixed owner: rebound under WorldTraceWriter._lock from whichever
# control-plane thread folds a rank's batch.
threadcheck.install(WorldTraceWriter, "spans_written",
                    "trace.WorldTraceWriter.spans_written")
