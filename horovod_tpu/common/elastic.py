"""Elastic worlds: survive preemption and re-rendezvous instead of
aborting the job (upstream analog: Elastic Horovod, the v0.20
fault-tolerance successor of the base system).

PR 2 made peer death FAIL FAST: heartbeats + tree-fanned ABORT turn a
SIGKILL'd rank into a structured :class:`WorldAbortedError` naming the
origin on every survivor within the heartbeat deadline. This module
makes that error RECOVERABLE. With ``HOROVOD_ELASTIC=1``:

1. Every rank binds a small **elastic listener** at init and the
   controller handshake distributes the full rank -> (host, port)
   endpoint map (the :class:`Membership` rank table — world-replicated
   state, installed only from broadcast-identical inputs).
2. On abort, survivors tear the old runtime down and enter the
   **re-rendezvous barrier**: the coordinator — or, when rank 0 died,
   the lowest surviving rank, elected deterministically from the PR 2
   origin attribution (candidates are swept in ascending old-rank
   order; a candidate whose elastic listener refuses the dial is dead,
   because listeners live for the whole process) — collects survivor
   manifests within ``HOROVOD_ELASTIC_WINDOW`` seconds, re-assigns
   dense ranks, binds a fresh controller listener and broadcasts a
   verdict.
3. Every member re-initializes through the ordinary init path: new
   controller channels (flat/hierarchical), new backends, and a
   response cache whose epoch is seeded from the new world GENERATION,
   so stale frames from the previous world fail fast through the
   existing epoch machinery (steady predictor, replay plans, fusion
   arenas and native steady plans all key off that epoch and die with
   the old runtime).
4. :func:`run` wraps the training function: it catches
   ``WorldAbortedError``, drives recovery, restores the
   :class:`State` to its last commit, re-broadcasts it from the new
   rank 0 (late rejoiners resync parameters the same way) and resumes.
   Below ``HOROVOD_ELASTIC_MIN_WORLD`` survivors the job aborts for
   real. ``HOROVOD_ELASTIC=0`` (the default) leaves the PR 2
   fail-fast behavior completely untouched.

Rejoins: a respawned process (``HOROVOD_ELASTIC_JOIN=1`` +
``HOROVOD_ELASTIC_JOIN_ADDR/PORT``, exported by the launcher's
supervision loop) dials the coordinator's elastic listener and parks a
join manifest there; the coordinator's background loop notices, fans a
benign "elastic-resize" abort, and the next barrier admits the joiner
with a fresh dense rank. A non-coordinator that receives a join dial
answers with a REDIRECT verdict carrying the current coordinator's
endpoint, so a launcher only ever needs one stable address.

Threading contract: the context is created under ``basics._lock``
during init; afterwards the background loop (join poll) and the
recovery path (which runs strictly after that loop has exited) are the
only writers, so no module lock is needed.
"""

from __future__ import annotations

import copy
import select
import socket
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import faults
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network
from horovod_tpu.common import wire
from horovod_tpu.common.config import Config
from horovod_tpu.common.invariants import world_coherent
from horovod_tpu.common.status import WorldAbortedError, world_abort_message

# Rendezvous frames ride their own short-lived sockets, framed by
# network.Channel; the tag value intentionally matches the controller's
# TAG_HANDSHAKE (1) — both are "identity exchange" frames and the two
# planes never share a socket.
RDZV_TAG = 1

# Verdict kinds (wire.serialize_elastic_verdict).
VERDICT_OK = 0        # assignment: join the new world
VERDICT_ABORT = 1     # world below the min floor: abort for real
VERDICT_REDIRECT = 2  # dialed a non-coordinator: retry at (addr, port)

# Manifest kinds (wire.serialize_elastic_manifest).
MANIFEST_SURVIVOR = 0
MANIFEST_JOIN = 1

_BARRIER_ACCEPT_SLICE_S = 0.2   # listener accept timeout per sweep
_MANIFEST_RECV_TIMEOUT_S = 5.0  # a dialer sends its manifest at once
_SWEEP_PAUSE_S = 0.1            # pause between election sweeps
_DIAL_TIMEOUT_S = 2.0           # per-candidate connect timeout


class Membership:
    """The world-replicated membership record: who is in the current
    world, at which generation, and which members past resizes lost.
    Installed ONLY from broadcast-identical inputs — the coordinator's
    init-time endpoint map or a rendezvous verdict — so every rank's
    copy is bit-identical (enforced by hvdlint's world-coherence
    analyzer through :func:`world_coherent`)."""

    def __init__(self):
        # new-world rank -> (host, elastic_port) of that member
        self.rank_table: Dict[int, Tuple[str, int]] = \
            {}  # hvdlint: world-replicated
        self.generation = 0  # hvdlint: world-replicated
        self.size = 0  # hvdlint: world-replicated
        # "gen:g rank r (host)" per member lost at each resize — the
        # world-converged view of the launcher's host blacklist
        self.blacklist: List[str] = []  # hvdlint: world-replicated

    @world_coherent
    def install(self, generation: int, size: int,
                rank_table: Dict[int, Tuple[str, int]],
                lost: Optional[List[str]] = None) -> None:
        """Adopt a new world membership. Inputs come exclusively from
        the coordinator's broadcast (handshake endpoint map or
        rendezvous verdict), identical on every member."""
        self.rank_table = dict(rank_table)
        self.generation = generation
        self.size = size
        if lost:
            self.blacklist.extend(lost)


class ElasticContext:
    """Process-global elastic state: the always-bound elastic listener,
    the membership table, pending join manifests and the counters the
    metrics plane mirrors. One per process, living across re-inits."""

    def __init__(self, cfg: Config, secret: bytes):
        self.enabled = True
        self.window_s = cfg.elastic_window_s
        self.min_world = max(1, cfg.elastic_min_world)
        self.secret = secret
        self.start_timeout = cfg.start_timeout
        # The elastic listener lives for the whole process: election
        # treats "connection refused" as proof of death, which is only
        # sound because a live member is always accept(2)able.
        self.listener = network.listen(cfg.elastic_port)
        self.port = self.listener.getsockname()[1]
        self.membership = Membership()
        self.rank = -1  # current-generation rank of this process
        # join manifests parked by the background loop's poll, consumed
        # by the next rendezvous barrier: [(Channel, manifest dict)]
        self.pending_joins: List[tuple] = []
        self.joined_as_rejoiner = False
        self._join_synced = False
        # observability (mirrored onto the PR 4 metrics plane)
        self.resizes = 0            # barriers run by THIS process
        self.rejoins_admitted = 0   # joiners admitted by THIS process
        self.last_resize_cause = ""
        self.last_rendezvous_s = 0.0
        self._unobserved_rdzv: List[float] = []
        # rejoin fast-sync observability (common/selfop.py)
        self.syncs = 0
        self.sync_bytes_total = 0
        self.last_sync_s = 0.0
        self._unobserved_sync: List[Tuple[float, int]] = []

    # -- membership ------------------------------------------------------
    @world_coherent
    def apply_membership(self, generation: int, rank: int, size: int,
                         rank_table: Dict[int, Tuple[str, int]],
                         lost: Optional[List[str]] = None) -> None:
        """Install a new world view. ``rank_table``/``lost`` are the
        coordinator's broadcast; ``rank`` is this member's dense rank
        inside it (per-rank by definition, not replicated)."""
        self.rank = rank
        self.membership.install(generation, size, rank_table, lost)
        # Flight-recorder breadcrumb (common/trace.py, on by
        # default): a postmortem dump then shows every generation
        # this process lived through, with its rank in each.
        from horovod_tpu.common import trace as htrace
        htrace.flight().record(
            htrace.EV_ELASTIC, arg=generation,
            note=f"membership installed: generation {generation}, "
                 f"rank {rank} of {size}")
        # The renumbering invalidates every per-rank clock offset:
        # old rank 3's skew must not bind to whoever is rank 2 now.
        htrace.clock().reset()

    def world_line(self) -> str:
        """One status line for the stall report."""
        m = self.membership
        line = (f"elastic: generation {m.generation}, "
                f"world size {m.size}")
        if self.last_resize_cause:
            line += f", last resize: {self.last_resize_cause}"
        if m.blacklist:
            line += f", lost members: {m.blacklist}"
        return line

    def take_rendezvous_observations(self) -> List[float]:
        out, self._unobserved_rdzv = self._unobserved_rdzv, []
        return out

    def note_sync(self, dt_s: float, nbytes: int) -> None:
        """One completed fast rejoin sync (duration, payload bytes)."""
        self.syncs += 1
        self.sync_bytes_total += nbytes
        self.last_sync_s = dt_s
        self._unobserved_sync.append((dt_s, nbytes))

    def take_sync_observations(self) -> List[Tuple[float, int]]:
        out, self._unobserved_sync = self._unobserved_sync, []
        return out

    # -- join polling (background loop, coordinator + redirectors) -------
    def poll_joins(self, is_coordinator: bool) -> Optional[str]:
        """Non-blocking sweep of the elastic listener. The coordinator
        parks join manifests and returns a resize cause (the caller
        fans a benign world abort so every member reaches the
        barrier); any other rank answers with a REDIRECT verdict at
        the current coordinator's endpoint. Returns None when nothing
        warrants a resize."""
        cause = None
        while True:
            try:
                p = select.poll()
                p.register(self.listener.fileno(), select.POLLIN)
                if not p.poll(0):
                    return cause
                sock, _ = self.listener.accept()
            except OSError:
                return cause
            if not self.membership.rank_table:
                # Elastic was requested but this world never installed
                # a membership (mixed knobs withheld the endpoint
                # map): there is nothing to resize INTO — refuse the
                # dial instead of letting one stray connection fan an
                # abort through a healthy world.
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            got = self._read_manifest(sock)
            if got is None:
                continue
            ch, m = got
            if not is_coordinator:
                coord = self.membership.rank_table.get(0)
                try:
                    if coord is not None:
                        ch.send(wire.serialize_elastic_verdict(
                            VERDICT_REDIRECT, self.membership.generation,
                            -1, 0, coord[0], coord[1],
                            "not the coordinator"), RDZV_TAG)
                finally:
                    ch.close()
                continue
            self.pending_joins.append((ch, m))
            kind = ("rejoining" if m["kind"] == MANIFEST_JOIN
                    else "re-admitting a stale member")
            cause = (f"elastic-resize: worker {kind} at the next "
                     f"rendezvous barrier")
        return cause

    def _read_manifest(self, sock) -> Optional[tuple]:
        """One manifest frame off a freshly accepted dial; garbage or a
        dead dialer is dropped without disturbing the world. The
        dialer's observed peer address overrides the self-reported
        host — it is the address this process provably can dial
        back, which is what the rank table is for."""
        try:
            sock.settimeout(_MANIFEST_RECV_TIMEOUT_S)
            ch = network.Channel(sock, self.secret)
            tag, payload = ch.recv()
            if tag != RDZV_TAG:
                raise ConnectionError(f"unexpected tag {tag}")
            m = wire.parse_elastic_manifest(payload)
            peer_ip = sock.getpeername()[0]
            if peer_ip:
                m["host"] = peer_ip
            sock.settimeout(None)
            return ch, m
        except (ConnectionError, OSError, socket.timeout, ValueError):
            try:
                sock.close()
            except OSError:
                pass
            return None

    def close(self) -> None:
        for ch, _ in self.pending_joins:
            try:
                ch.close()
            except OSError:
                pass  # stage-guarded: the listener must still close
        self.pending_joins = []
        try:
            self.listener.close()
        except OSError:
            pass


_ctx: Optional[ElasticContext] = None


def context() -> Optional[ElasticContext]:
    """The live elastic context (None when HOROVOD_ELASTIC is off or
    init has not run)."""
    return _ctx


def enabled() -> bool:
    return _ctx is not None


def generation() -> int:
    """Current world generation (0 for the first world and whenever
    elastic mode is off). The response-cache epoch is seeded from this
    so control frames of a previous generation fail the existing
    epoch equality gates instead of silently negotiating."""
    return 0 if _ctx is None else _ctx.membership.generation


def ensure_context(cfg: Config, secret: bytes) -> ElasticContext:
    """Create (once per process) the elastic context. Called from
    basics.init under its init lock."""
    global _ctx
    if _ctx is None:
        _ctx = ElasticContext(cfg, secret)
    return _ctx


def reset() -> None:
    """Test hook: drop the process-global context."""
    global _ctx
    if _ctx is not None:
        _ctx.close()
    _ctx = None


def my_endpoint_port() -> Optional[int]:
    return None if _ctx is None else _ctx.port


# -- rendezvous barrier ------------------------------------------------------

def _fatal_abort(reason: str) -> WorldAbortedError:
    """A TERMINAL elastic failure (window expired, world below the
    floor): :func:`run` must propagate it instead of attempting yet
    another recovery round."""
    err = WorldAbortedError(world_abort_message(-1, reason),
                            origin_rank=-1, cause=reason)
    err.elastic_fatal = True
    return err


class _Assignment:
    """What a member leaves the barrier with: enough to re-init."""

    __slots__ = ("generation", "rank", "size", "controller_addr",
                 "controller_port", "listener", "cause", "lost",
                 "coord_elastic_port", "demote_rank", "pace_us")

    def __init__(self, generation: int, rank: int, size: int,
                 controller_addr: str, controller_port: int,
                 listener=None, cause: str = "", lost=None,
                 coord_elastic_port: int = 0, demote_rank: int = -1,
                 pace_us: int = 0):
        self.generation = generation
        self.rank = rank
        self.size = size
        self.controller_addr = controller_addr
        self.controller_port = controller_port
        self.listener = listener  # pre-bound controller listener (rank 0)
        self.cause = cause
        self.lost = lost or []
        # The new coordinator's ELASTIC listener: a follower whose
        # re-init fails can re-enter recovery against it even before
        # the full endpoint map arrives via the init handshake.
        self.coord_elastic_port = coord_elastic_port
        # Supervision verdict riding the resize (common/selfop.py):
        # the NEW rank of a demoted habitual straggler, and the pacing
        # every other member applies per cycle (-1/0 = none).
        self.demote_rank = demote_rank
        self.pace_us = pace_us


def _install_selfop_verdict(generation: int, cause: str,
                            demote_rank: int, pace_us: int) -> None:
    """Install the supervision verdict carried by a resize on THIS
    member. Inputs come exclusively from the coordinator's verdict
    broadcast (or its own pending decision it just broadcast), so the
    install is world-coherent by construction. A resize with no
    decision installs the empty verdict — pacing never leaks across
    unrelated generations."""
    from horovod_tpu.common import selfop
    if demote_rank >= 0:
        selfop.verdict().install("demote", demote_rank, generation,
                                 cause, pace_us)
    else:
        selfop.verdict().install("", -1, generation, "", 0)


def _coordinate_barrier(ctx: ElasticContext, cause: str,
                        deadline: float, dead: set) -> _Assignment:
    """Run the re-rendezvous barrier as the elected coordinator:
    collect survivor manifests (and pending joins) until everyone
    expected arrived or the window expires, re-assign dense ranks,
    bind a fresh controller listener and broadcast the verdict."""
    t0 = time.monotonic()
    table = ctx.membership.rank_table
    my_host = table.get(ctx.rank, ("127.0.0.1", ctx.port))[0]
    expected = {r for r in table
                if r not in dead and r != ctx.rank}
    # old_rank -> (manifest, channel|None); joiners keyed separately
    members: Dict[int, tuple] = {
        ctx.rank: ({"kind": MANIFEST_SURVIVOR, "gen":
                    ctx.membership.generation, "old_rank": ctx.rank,
                    "host": my_host, "elastic_port": ctx.port}, None)}
    joiners: List[tuple] = []

    def _admit(m: dict, ch) -> None:
        """One classification for parked AND freshly accepted
        manifests: a current-generation survivor takes its expected
        slot (it may have dialed EARLY — before this coordinator's
        own abort — and been parked by the join poll); everything
        else (a joiner, a stale-generation straggler, a duplicate) is
        admitted as a fresh member at the tail."""
        if (m["kind"] == MANIFEST_SURVIVOR
                and m["gen"] == ctx.membership.generation
                and m["old_rank"] in expected
                and m["old_rank"] not in members):
            members[m["old_rank"]] = (m, ch)
        else:
            joiners.append((m, ch))

    pending, ctx.pending_joins = ctx.pending_joins, []
    for ch, m in pending:
        _admit(m, ch)
    ctx.listener.settimeout(_BARRIER_ACCEPT_SLICE_S)
    try:
        while time.monotonic() < deadline and expected - set(members):
            try:
                sock, _ = ctx.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            got = ctx._read_manifest(sock)
            if got is None:
                continue
            _admit(got[1], got[0])
    finally:
        ctx.listener.settimeout(None)

    survivors = sorted(members)
    lost = [f"gen:{ctx.membership.generation} rank {r} "
            f"({table[r][0]})"
            for r in sorted(set(table) - set(survivors))]
    # A pending supervision demotion (common/selfop.py) reorders the
    # habitual straggler to the survivor tail, where the ring/tree
    # topologies place the leaf/tail role. The coordinator must keep
    # slot 0 — the election invariant — so it is never the target.
    demote_old, demote_new, pace_us = -1, -1, 0
    from horovod_tpu.common import selfop
    pol = selfop.policy()
    if pol is not None:
        pending = pol.take_pending_demote()
        if pending is not None and pending[0] in members \
                and pending[0] != ctx.rank and len(survivors) > 1:
            demote_old, pace_us = pending
            survivors = [r for r in survivors if r != demote_old] \
                + [demote_old]
            demote_new = len(survivors) - 1
    new_size = len(survivors) + len(joiners)
    gen2 = ctx.membership.generation + 1
    if new_size < ctx.min_world:
        reason = (f"elastic world shrank to {new_size} member(s), "
                  f"below HOROVOD_ELASTIC_MIN_WORLD="
                  f"{ctx.min_world} (after: {cause})")
        for _, ch in list(members.values()) + joiners:
            if ch is None:
                continue
            try:
                ch.send(wire.serialize_elastic_verdict(
                    VERDICT_ABORT, gen2, -1, new_size, "", 0, reason),
                    RDZV_TAG)
            except (ConnectionError, OSError):
                pass
            ch.close()
        raise _fatal_abort(reason)

    listener = network.listen(0)
    port = listener.getsockname()[1]
    new_ranks: List[tuple] = []  # (new_rank, manifest, channel)
    for i, r in enumerate(survivors):
        m, ch = members[r]
        new_ranks.append((i, m, ch))
    for j, (m, ch) in enumerate(joiners):
        new_ranks.append((len(survivors) + j, m, ch))
    table2 = {nr: (m["host"], m["elastic_port"])
              for nr, m, _ in new_ranks}
    for nr, _, ch in new_ranks:
        if ch is None:
            continue  # self
        try:
            ch.send(wire.serialize_elastic_verdict(
                VERDICT_OK, gen2, nr, new_size, my_host, port, cause,
                lost=lost, joined=len(joiners),
                coord_elastic_port=ctx.port, demote_rank=demote_new,
                pace_us=pace_us), RDZV_TAG)
        except (ConnectionError, OSError):
            # died between manifest and verdict: it will come back (or
            # not) through the join path; the new world forms without
            # waiting — a second resize re-admits it.
            pass
        ch.close()
    ctx.resizes += 1
    ctx.rejoins_admitted += len(joiners)
    ctx.last_resize_cause = cause
    ctx.last_rendezvous_s = time.monotonic() - t0
    ctx.apply_membership(gen2, 0, new_size, table2, lost=lost)
    _install_selfop_verdict(gen2, cause, demote_new, pace_us)
    hlog.warning(
        f"elastic re-rendezvous complete: generation {gen2}, "
        f"{len(survivors)} survivor(s) + {len(joiners)} rejoin(s) "
        f"-> world size {new_size} "
        f"({ctx.last_rendezvous_s * 1000:.0f} ms barrier); "
        f"cause: {cause}", rank=ctx.rank)
    return _Assignment(gen2, 0, new_size, my_host, port,
                       listener=listener, cause=cause,
                       coord_elastic_port=ctx.port,
                       demote_rank=demote_new, pace_us=pace_us)


def _follow_barrier(ctx: ElasticContext, candidate: int,
                    deadline: float, kind: int = MANIFEST_SURVIVOR,
                    endpoint: Optional[Tuple[str, int]] = None):
    """Dial ``candidate``'s elastic listener, park a manifest, await
    the verdict. Returns an _Assignment, the string "dead" (dial
    REFUSED, or the accepted channel died mid-barrier: exclude and
    move on), the string "retry" (dial timed out / host unreachable:
    the candidate may be alive-but-unresponsive, so the election must
    NOT step past it — self-electing on a timeout would split the
    brain; the sweep restarts and a truly lost world ends at the
    window), or a (host, port) redirect target."""
    host, port = endpoint if endpoint is not None \
        else ctx.membership.rank_table[candidate]
    try:
        sock = socket.create_connection((host, port),
                                        timeout=_DIAL_TIMEOUT_S)
    except ConnectionRefusedError:
        # The listener lives for the candidate's whole process life:
        # an active refusal is proof of death — the invariant the
        # deterministic election rests on.
        return "dead"
    except (OSError, socket.timeout):
        return "retry"
    sock.settimeout(None)
    ch = network.Channel(sock, ctx.secret, peer=f"{host}:{port}")
    try:
        me = ctx.membership.rank_table.get(ctx.rank)
        my_host = me[0] if me is not None else "127.0.0.1"
        ch.send(wire.serialize_elastic_manifest(
            kind, ctx.membership.generation, ctx.rank, my_host,
            ctx.port), RDZV_TAG)
        # The verdict arrives only when the barrier closes — wait out
        # the remaining window plus slack for the coordinator's own
        # teardown/window.
        wait = max(1.0, deadline - time.monotonic()) + ctx.window_s \
            + 5.0
        ch.sock.settimeout(wait)
        tag, payload = ch.recv()
        if tag != RDZV_TAG:
            raise ConnectionError(f"unexpected tag {tag}")
        v = wire.parse_elastic_verdict(payload)
    except (ConnectionError, OSError, socket.timeout):
        return "dead"
    finally:
        ch.close()
    if v["verdict"] == VERDICT_ABORT:
        raise _fatal_abort(v["cause"])
    if v["verdict"] == VERDICT_REDIRECT:
        return (v["addr"], v["port"])
    return _Assignment(v["gen"], v["rank"], v["size"], v["addr"],
                       v["port"], cause=v["cause"], lost=v["lost"],
                       coord_elastic_port=v["coord_elastic_port"],
                       demote_rank=v["demote_rank"],
                       pace_us=v["pace_us"])


def rendezvous(origin_rank: int, cause: str) -> _Assignment:
    """The re-rendezvous barrier, entered by every survivor after the
    old runtime is torn down. Election is deterministic: candidates
    are swept in ascending old-rank order, skipping ranks known dead
    (the PR 2 origin attribution plus refused dials); the first live
    candidate is the coordinator — each process that reaches its own
    rank in the sweep coordinates, everyone else follows."""
    ctx = _ctx
    assert ctx is not None
    t0 = time.monotonic()
    from horovod_tpu.common import trace as htrace
    htrace.flight().record(
        htrace.EV_ELASTIC,
        arg=origin_rank if origin_rank is not None else -1,
        note=f"entering re-rendezvous (cause: {cause[:120]})")
    faults.tick_rendezvous(ctx.rank)
    dead = set()
    if origin_rank is not None and origin_rank >= 0:
        dead.add(origin_rank)
    deadline = t0 + ctx.window_s
    while time.monotonic() < deadline:
        cands = [r for r in sorted(ctx.membership.rank_table)
                 if r not in dead]
        if ctx.rank not in cands:
            break  # everyone else presumed dead would still include us
        restart_sweep = False
        for c in cands:
            if c == ctx.rank:
                a = _coordinate_barrier(ctx, cause, deadline, dead)
                ctx.last_rendezvous_s = time.monotonic() - t0
                ctx._unobserved_rdzv.append(ctx.last_rendezvous_s)
                return a
            res = _follow_barrier(ctx, c, deadline)
            if res == "dead":
                dead.add(c)
                continue
            if res == "retry" or isinstance(res, tuple):
                # REDIRECT (the candidate is alive but has not entered
                # recovery yet — its runtime answered the dial) or an
                # ambiguous timeout (alive-but-unresponsive?). Either
                # way the candidate may still be the rightful
                # coordinator — restarting the sweep, never falling
                # through past it, is what keeps the election
                # split-brain-free; a truly lost world ends at the
                # window expiry instead.
                restart_sweep = True
                break
            ctx.last_resize_cause = cause
            ctx.last_rendezvous_s = time.monotonic() - t0
            ctx._unobserved_rdzv.append(ctx.last_rendezvous_s)
            ctx.apply_membership(res.generation, res.rank, res.size,
                                 _table_placeholder(res, ctx),
                                 lost=res.lost)
            _install_selfop_verdict(res.generation, res.cause,
                                    res.demote_rank, res.pace_us)
            hlog.warning(
                f"elastic re-rendezvous complete: generation "
                f"{res.generation}, new rank {res.rank} of "
                f"{res.size} ({ctx.last_rendezvous_s * 1000:.0f} ms); "
                f"cause: {cause}", rank=res.rank)
            return res
        if restart_sweep:
            time.sleep(_SWEEP_PAUSE_S)
    reason = (f"elastic re-rendezvous failed within "
              f"HOROVOD_ELASTIC_WINDOW={ctx.window_s:g}s "
              f"(no live coordinator candidate; after: {cause})")
    raise _fatal_abort(reason)


def _table_placeholder(a: _Assignment,
                       ctx: ElasticContext
                       ) -> Dict[int, Tuple[str, int]]:
    """A follower's rank table between verdict and re-init: the new
    coordinator's DIALABLE elastic endpoint plus this member's own —
    enough that a failure during re-init (another member dying before
    the handshake completes) can run a further recovery round instead
    of finding no candidates. The full map is installed from the init
    handshake moments later."""
    table = {0: (a.controller_addr, a.coord_elastic_port)}
    if a.rank != 0:
        me = ctx.membership.rank_table.get(ctx.rank)
        table[a.rank] = (me[0] if me is not None else "127.0.0.1",
                         ctx.port)
    return table


def join_world(cfg: Config, secret: bytes) -> _Assignment:
    """Joiner path (HOROVOD_ELASTIC_JOIN=1): dial the advertised
    coordinator endpoint, park a join manifest, follow redirects, and
    wait for the next rendezvous barrier to admit us."""
    ctx = ensure_context(cfg, secret)
    addr = cfg.elastic_join_addr or cfg.controller_addr or "127.0.0.1"
    port = cfg.elastic_join_port
    if port <= 0:
        raise ValueError(
            "HOROVOD_ELASTIC_JOIN=1 needs HOROVOD_ELASTIC_JOIN_PORT "
            "(the coordinator's elastic listener; the hvdtpurun "
            "--elastic supervision loop exports it)")
    deadline = time.monotonic() + max(cfg.elastic_window_s,
                                      cfg.start_timeout)
    ctx.rank = -1
    target = (addr, port)
    delays = network.backoff_delays(base=0.1, cap=1.0)
    while time.monotonic() < deadline:
        res = _follow_barrier(ctx, -1, deadline, kind=MANIFEST_JOIN,
                              endpoint=target)
        if isinstance(res, _Assignment):
            ctx.joined_as_rejoiner = True
            ctx.last_resize_cause = res.cause
            ctx.apply_membership(res.generation, res.rank, res.size,
                                 _table_placeholder(res, ctx),
                                 lost=res.lost)
            _install_selfop_verdict(res.generation, res.cause,
                                    res.demote_rank, res.pace_us)
            return res
        if isinstance(res, tuple):
            target = res  # redirect to the live coordinator
            continue
        time.sleep(min(next(delays),
                       max(0.0, deadline - time.monotonic())))
    raise WorldAbortedError(
        world_abort_message(-1, "elastic join timed out"),
        origin_rank=-1,
        cause=(f"could not join an elastic world at {target[0]}:"
               f"{target[1]} within the window"))


# -- user-facing API ---------------------------------------------------------

class State:
    """Training state carried across resizes: parameters, optimizer
    state, batch/epoch counters — anything numpy-shaped or scalar.

    ``commit()`` snapshots, ``restore()`` rolls back to the last
    commit (survivors roll back work the dead rank never contributed
    to), and ``sync()`` broadcasts every value from rank 0 of the new
    world so survivors and late rejoiners resume bit-identical."""

    def __init__(self, **values):
        object.__setattr__(self, "_values", dict(values))
        object.__setattr__(self, "_committed", copy.deepcopy(values))
        # Monotonic commit counter: the async checkpoint writer
        # (common/selfop.py) keys shard files on it so every rank's
        # shard of one training step shares a sequence number.
        object.__setattr__(self, "_commit_seq", 0)

    def __getattr__(self, name):
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        object.__getattribute__(self, "_values")[name] = value

    def commit(self) -> None:
        object.__setattr__(self, "_committed",
                           copy.deepcopy(object.__getattribute__(
                               self, "_values")))
        object.__setattr__(self, "_commit_seq",
                           object.__getattribute__(
                               self, "_commit_seq") + 1)

    def restore(self) -> None:
        object.__setattr__(self, "_values",
                           copy.deepcopy(object.__getattribute__(
                               self, "_committed")))

    def sync(self) -> None:
        """Broadcast every value from rank 0 (deterministic key order
        on every member) and commit the result. New members pass
        same-shaped placeholders constructed by their own user code —
        the broadcast overwrites them.

        Large states ride the chunked, tree-pipelined, zero-copy fast
        path (common/selfop.py); it declines world-consistently (the
        root broadcasts an empty manifest) below its size floor or
        when disabled, falling back to the legacy per-key broadcast."""
        from horovod_tpu.common import selfop
        if selfop.sync_state(self):
            return
        self._sync_broadcast()
        self.commit()

    def _sync_broadcast(self, keys=None) -> None:
        """The legacy per-key broadcast leg. ``keys=None`` covers the
        whole state; the fast path passes just the keys its manifest
        could not describe (non-contiguous arrays, arbitrary
        objects). Does NOT commit — the caller owns that."""
        from horovod_tpu import ops
        vals = object.__getattribute__(self, "_values")
        gen = generation()
        for key in (sorted(vals) if keys is None else keys):
            v = vals[key]
            out = ops.broadcast(np.asarray(v), root_rank=0,
                                name=f"elastic.sync.g{gen}.{key}")
            if isinstance(v, np.ndarray):
                vals[key] = out
            elif isinstance(v, (bool, int, float)) or np.isscalar(v):
                vals[key] = type(v)(out.item())
            else:
                vals[key] = out


def _recover(err: WorldAbortedError) -> None:
    """Tear the dead runtime down, re-rendezvous, re-init, done.
    Raises the (possibly new) WorldAbortedError when the world cannot
    be re-formed."""
    from horovod_tpu.common import basics
    ctx = _ctx
    if not ctx.membership.rank_table:
        # Elastic was requested but the world never exchanged an
        # endpoint map (mixed knobs, size-1 world): fail fast —
        # terminally, there is no membership to recover with.
        err.elastic_fatal = True
        raise err
    origin = getattr(err, "origin_rank", -1)
    cause = getattr(err, "cause", str(err))
    hlog.warning(
        f"elastic recovery engaged (origin rank {origin}): {cause}",
        rank=ctx.rank)
    basics.shutdown()
    assignment = rendezvous(origin, cause)
    cfg = Config.from_env()
    cfg.elastic_join = False  # a member re-inits, it does not re-join
    cfg.rank = assignment.rank
    cfg.size = assignment.size
    cfg.controller_addr = assignment.controller_addr
    cfg.controller_port = assignment.controller_port
    cfg.controller_fd = -1
    basics.init(config=cfg,
                coordinator_listener=assignment.listener)


def run(func):
    """Decorator making a training function elastic::

        state = hvd.elastic.State(params=..., batch=0)

        @hvd.elastic.run
        def train(state):
            while state.batch < total:
                step(state); state.batch += 1; state.commit()

        train(state)

    On :class:`WorldAbortedError` the wrapper re-rendezvouses the
    survivors into a shrunk world (or admits rejoiners into a grown
    one), restores ``state`` to its last commit, re-broadcasts it from
    the new rank 0 and calls ``func`` again. With elastic mode off the
    error propagates unchanged — today's fail-fast behavior."""

    def wrapper(state: State, *args, **kwargs):
        from horovod_tpu.common import selfop
        ctx = _ctx
        # Async checkpointing (common/selfop.py): the runtime's idle
        # windows persist this state's committed shards; a supervised
        # restart after a below-min-world death resumes from them.
        selfop.register_state(state)
        if object.__getattribute__(state, "_commit_seq") == 0:
            ckpt_dir = selfop.checkpoint_dir()
            if ckpt_dir:
                selfop.restore_state(state, ckpt_dir)
        if ctx is not None and ctx.joined_as_rejoiner \
                and not ctx._join_synced:
            # A joiner's first act is the SAME State broadcast the
            # survivors run at the end of their recovery — parameters
            # and counters arrive from rank 0 before any training.
            ctx._join_synced = True
            state.sync()
        while True:
            try:
                return func(state, *args, **kwargs)
            except WorldAbortedError as e:
                if _ctx is None:
                    raise
                # A preempted member drains to here with its last
                # commit intact; it retires cleanly (exit 0 — the
                # launcher never respawns a clean exit) instead of
                # rejoining the world that is resizing around it.
                selfop.retire_if_preempted()
                err = e
                # Recovery may itself be interrupted — another member
                # dying during state.sync() or between the verdict and
                # re-init surfaces as a fresh abort/transport error,
                # and the answer is another recovery round, not death.
                # Only a TERMINAL failure (_fatal_abort: rendezvous
                # window expired, world below the min floor)
                # propagates; a truly lost world always reaches one,
                # because every retry re-runs the bounded rendezvous.
                while True:
                    selfop.retire_if_preempted()
                    try:
                        _recover(err)
                        state.restore()
                        state.sync()
                        break
                    except WorldAbortedError as e2:
                        if getattr(e2, "elastic_fatal", False):
                            raise
                        err = e2
                    except (ConnectionError, OSError,
                            TimeoutError) as e2:
                        cause = (f"world re-initialization failed: "
                                 f"{e2}")
                        err = WorldAbortedError(
                            world_abort_message(-1, cause),
                            origin_rank=-1, cause=cause)

    wrapper.__name__ = getattr(func, "__name__", "elastic_run")
    wrapper.__doc__ = func.__doc__
    return wrapper
