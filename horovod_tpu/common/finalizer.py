"""Asynchronous collective completion.

TPU-native equivalent of the reference's CUDA finalizer threads
(reference: horovod/common/ops/cuda_operations.cc:148-179 —
``FinalizeCUDAQueue`` detaches a thread per batch that waits on the
recorded CUDA events, fires every entry's StatusCallback, and lets the
op return ``Status::InProgress()`` so the background loop keeps
negotiating the next cycle instead of blocking on the collective).

On TPU the data plane is an XLA computation whose dispatch is already
asynchronous; what must not block is the *negotiation loop*. A backend
that wants async completion issues its computation, registers a
completion closure here (typically ``jax.block_until_ready`` on the
output arrays followed by the callbacks), and returns
``Status.InProgress()``. One detached thread per batch mirrors the
reference and avoids head-of-line blocking: a small batch issued after
a huge allreduce may complete first, exactly as with per-batch CUDA
finalizers.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List

from horovod_tpu.common import lockdep
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import threadcheck


class Finalizer:
    """Detached per-batch completion threads with a drainable registry."""

    def __init__(self):
        self._lock = lockdep.lock("finalizer.Finalizer._lock")
        self._threads: List[threading.Thread] = []
        self._closed = False

    def submit(self, fn: Callable[[], None]) -> bool:
        """Run ``fn`` on a detached finalizer thread. Returns False when
        draining has begun — the caller must then complete synchronously."""
        t = threading.Thread(target=self._run, args=(fn,),
                             name="hvd-finalizer", daemon=True)
        with self._lock:
            if self._closed:
                return False
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            # Start under the lock so a concurrent drain() can never
            # observe (and join) a registered-but-unstarted thread.
            t.start()
        return True

    @staticmethod
    def _run(fn: Callable[[], None]) -> None:
        threadcheck.register_role("hvd-finalizer")
        try:
            fn()
        except Exception as e:  # a closure must never kill the process
            hlog.error(f"finalizer task failed: {e!r}")

    def drain(self, timeout: float = 30.0) -> None:
        """Refuse new work and wait for in-flight completions — called
        from the background loop's shutdown path so every issued
        collective still fires its callbacks before SHUT_DOWN fan-out."""
        with self._lock:
            self._closed = True
            threads = list(self._threads)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        stuck = [t.name for t in threads if t.is_alive()]
        if stuck:
            # Callbacks of these batches will never fire; any
            # synchronize() on their handles is hung — say so.
            hlog.error(
                f"finalizer drain timed out after {timeout}s with "
                f"{len(stuck)} completion thread(s) still running; "
                "their collectives' callbacks will not fire")
