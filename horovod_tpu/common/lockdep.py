"""Runtime lock-order checker ("lockdep"): ``HOROVOD_TPU_LOCKCHECK``.

The dynamic half of hvdlint's static ``lock-order`` analyzer (see
docs/static_analysis.md): the static pass proves what it can resolve;
this wrapper observes what actually runs — callback indirection,
monkeypatched test seams, code paths the resolver cannot follow.

Design, following the kernel's lockdep: locks are grouped into
**classes by allocation-site name** (``"tensor_table.TensorTable
._lock"`` — the same identities the static analyzer reports, so a
runtime inversion and a static finding name the same thing). Each
thread keeps its held-class stack; acquiring B while holding A records
the world-visible edge A→B. The FIRST time the reverse edge of an
already-recorded edge is attempted, that acquisition is an observed
inversion: two threads interleaving those paths can deadlock. Modes:

* ``HOROVOD_TPU_LOCKCHECK=1`` (or ``raise``/``on``/``true``) — raise
  :class:`LockInversionError` *before* taking the lock, naming both
  orders with their witness threads. Armed in the multiprocess test
  worlds, so every mp scenario doubles as an inversion regression
  test.
* ``HOROVOD_TPU_LOCKCHECK=warn`` — log and count, never raise
  (production triage). Either mode feeds
  ``hvd_lockcheck_inversions_total`` on the metrics plane.
* unset/empty — :func:`lock` returns a plain ``threading.Lock``:
  zero steady-state overhead, nothing wrapped.

Same-class edges (two *instances* of one allocation site) are skipped:
instances are indistinguishable at class granularity and per-instance
tracking would make every ``Counter._lock`` pair a false cycle.
Conditions created via :func:`condition` share their lock's class, so
``with cv:`` and ``with lock:`` order-check as the one lock they are.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from horovod_tpu.common import config as hconfig


class LockInversionError(RuntimeError):
    """Observed lock acquisition-order inversion (latent deadlock)."""


_MODE_MAP = {"1": "raise", "true": "raise", "on": "raise",
             "raise": "raise", "warn": "warn"}
_mode: Optional[str] = None          # None = env not read yet
_graph_lock = threading.Lock()
# (first_class, then_class) -> thread name that witnessed the order
_edges: Dict[Tuple[str, str], str] = {}
_inversions = 0
_tls = threading.local()


def _get_mode() -> str:
    global _mode
    if _mode is None:
        raw = hconfig.env_str("HOROVOD_TPU_LOCKCHECK", "").strip().lower()
        if not raw and hconfig.env_str(
                "HOROVOD_TPU_THREADCHECK", "").strip():
            # The thread-affinity sanitizer (threadcheck.py) uses this
            # thread's held-lock stack as its "synchronized" witness;
            # plain unwrapped locks never feed it, so arming
            # threadcheck alone would turn every lock-protected
            # cross-role write into a false positive.
            raw = "warn"
        # hvdlint: owned-by=main -- idempotent lazy cache of one env read: every racing writer stores the same value, and reset() is test-only
        _mode = _MODE_MAP.get(raw, "")
    return _mode


def enabled() -> bool:
    return bool(_get_mode())


def inversion_count() -> int:
    """Lifetime observed inversions (mirrored to the metrics plane as
    hvd_lockcheck_inversions_total by the runtime's collector)."""
    return _inversions


def reset(mode: Optional[str] = None) -> None:
    """Tests only: drop the recorded graph/counter and re-read (or
    force) the mode."""
    global _mode, _inversions
    with _graph_lock:
        _edges.clear()
        _inversions = 0
    _mode = _MODE_MAP.get(mode, "") if mode is not None else None


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _note_acquire(name: str) -> None:
    """Record edges held->name; report on the first observed reverse.
    Runs BEFORE the underlying acquire, so ``raise`` mode refuses the
    inverting acquisition instead of deadlocking on it."""
    global _inversions
    held = _held()
    me = threading.current_thread().name
    for prev in held:
        if prev == name:
            continue  # same class: instances are indistinguishable
        with _graph_lock:
            witness = _edges.get((name, prev))
            if witness is not None:
                _inversions += 1
                count = _inversions
            else:
                _edges.setdefault((prev, name), me)
                continue
        msg = (f"lock-order inversion: thread {me!r} acquires "
               f"'{name}' while holding '{prev}', but thread "
               f"{witness!r} established the order '{name}' -> "
               f"'{prev}' — two threads interleaving these paths "
               f"deadlock (inversion #{count}; "
               f"HOROVOD_TPU_LOCKCHECK armed)")
        if _get_mode() == "raise":
            raise LockInversionError(msg)
        from horovod_tpu.common import logging as hlog
        hlog.warning(msg)


def _push(name: str) -> None:
    _held().append(name)


def _pop(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class _CheckedLock:
    """Order-checking wrapper. Exposes the small surface the codebase
    (and ``threading.Condition``) actually uses; Condition's fallback
    protocol drives plain ``acquire``/``release``, which keeps the
    held-stack exact across ``cv.wait()``'s release/reacquire."""

    __slots__ = ("_name", "_lock")

    def __init__(self, name: str, factory=threading.Lock):
        self._name = name
        self._lock = factory()

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # check/record first: refusing (or logging) the inverting
            # acquisition BEFORE blocking on it is what turns a latent
            # deadlock into a diagnosable error
            _note_acquire(self._name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _push(self._name)
        return ok

    def release(self) -> None:
        self._lock.release()
        _pop(self._name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "_CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<_CheckedLock {self._name!r} {self._lock!r}>"


def lock(name: str) -> "threading.Lock | _CheckedLock":
    """A lock belonging to lockdep class ``name``. Plain
    ``threading.Lock`` when lockcheck is off — call sites pay nothing
    for the instrumentation they are not using."""
    if not enabled():
        return threading.Lock()
    return _CheckedLock(name)


def rlock(name: str) -> "threading.RLock | _CheckedLock":
    if not enabled():
        return threading.RLock()
    return _CheckedLock(name, factory=threading.RLock)


def condition(name: str, lock_obj=None) -> threading.Condition:
    """A Condition order-checked under ``name`` (or sharing
    ``lock_obj``'s class when given — Condition(lock) IS that lock)."""
    if lock_obj is None:
        lock_obj = lock(name)
    return threading.Condition(lock_obj)
