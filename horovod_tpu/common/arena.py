"""Persistent fusion arenas: grow-only pack buffers for the data plane.

The reference keeps ONE long-lived fusion buffer per device and packs
every fused batch into it (reference: horovod/common/fusion_buffer_
manager.cc — allocated once, reused for the job's lifetime); this
module is that idea for the host data planes. An arena is a page-
aligned, grow-only numpy byte buffer: steady-state steps pack into the
same memory every cycle, so the per-step cost is one memcpy instead of
an allocation + memcpy, and the send-side iovec plans built over arena
pointers stay valid for the life of the plan (grown arenas re-allocate,
but numpy views keep the old base alive, so existing plans keep
working and new plans bind the new memory).

Aliasing contract (the rule the aliasing-correctness tests pin down):
arena memory only ever holds SEND-side packed bytes and coordinator
peer scratch. Receive destinations that user-visible outputs may alias
are always fresh per-op arrays — never arena memory — so a tensor
handed back by a collective is never clobbered by a later step.
"""

from __future__ import annotations

import weakref
from typing import List, Optional

import numpy as np

_PAGE = 4096

# Live arenas (weak), for the hvd_arena_bytes gauge: observability
# only — the metrics collector sums capacities once per snapshot.
_ARENAS: List["weakref.ref"] = []


def _pad(nbytes: int) -> int:
    return -(-max(nbytes, 1) // _PAGE) * _PAGE


class FusionArena:
    """One grow-only pack buffer.

    ``generation`` bumps on every re-allocation so memoized pointer
    plans (ctypes iovec bundles) know when their views bind an old
    allocation — old views stay VALID (numpy keeps the base alive),
    new plans should rebind via :meth:`view`.
    """

    __slots__ = ("_buf", "generation", "__weakref__")

    def __init__(self):
        self._buf: Optional[np.ndarray] = None
        self.generation = 0
        _ARENAS.append(weakref.ref(self))

    @property
    def nbytes(self) -> int:
        return 0 if self._buf is None else self._buf.nbytes

    def ensure(self, nbytes: int) -> None:
        """Grow (never shrink) to hold ``nbytes``. Doubling growth so
        a stream of slightly-increasing payloads re-allocates O(log)
        times, like the shm segment stride policy."""
        if self._buf is not None and self._buf.nbytes >= nbytes:
            return
        new = _pad(max(nbytes, 2 * self.nbytes))
        self._buf = np.empty(new, np.uint8)
        self.generation += 1

    def view(self, offset: int, nbytes: int) -> np.ndarray:
        """Writable uint8 view of [offset, offset+nbytes) — grows the
        arena if needed."""
        self.ensure(offset + nbytes)
        return self._buf[offset:offset + nbytes]

    def typed(self, offset: int, dtype, count: int) -> np.ndarray:
        """Writable typed view (zero-copy reinterpret of :meth:`view`;
        numpy extension dtypes like bfloat16 ride through .view)."""
        dtype = np.dtype(dtype)
        raw = self.view(offset, count * dtype.itemsize)
        return raw.view(dtype)


def concat_into(flats, dst) -> None:
    """Pack same-dtype flat arrays into ``dst`` (len == total size):
    one C-level gather-copy. Measurably cheaper than marshalling
    ctypes pointer arrays into the native pack at gradient-bucket
    sizes — building two 64-slot ctypes arrays costs more than the
    memcpys themselves. The element-wise fallback covers numpy builds
    whose ``concatenate(out=)`` rejects the destination view. THE one
    pack idiom both the classic host planes and the steady plans
    share."""
    try:
        np.concatenate(flats, out=dst)
    except (TypeError, ValueError):
        pos = 0
        for a in flats:
            dst[pos:pos + a.size] = a
            pos += a.size


def total_bytes() -> int:
    """Sum of live arena capacities (the hvd_arena_bytes gauge)."""
    total = 0
    dead = False
    for ref in _ARENAS:
        a = ref()
        if a is None:
            dead = True
            continue
        total += a.nbytes
    if dead:
        _ARENAS[:] = [r for r in _ARENAS if r() is not None]
    return total
