"""World-aggregated metrics plane: counters, gauges and histograms.

The reference ships three observability surfaces — the rank-0 Chrome
timeline, the stall inspector and the autotune log — and all three are
post-hoc: none answers "what is the world's cycle latency distribution,
cache hit rate, bytes/sec per backend, queue depth, per-peer heartbeat
age — *right now*" while the job runs. This module adds that layer:

* lock-cheap per-rank :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` objects handed out by a :class:`MetricsRegistry`;
* a compiled-out no-op path (``HOROVOD_TPU_METRICS``, default off):
  with metrics disabled the registry hands every call site the shared
  :data:`NOOP_METRIC`, whose hooks are empty methods — the same
  zero-overhead pattern as ``_NoOpTimeline`` (timeline.py);
* world aggregation riding the existing control tree the same way PING
  and CACHED_AGG frames do: each rank folds its snapshot into a compact
  METRICS frame (codec: wire.py) every ``HOROVOD_TPU_METRICS_INTERVAL``
  seconds, hierarchical local roots sum their host into ONE frame, and
  rank 0 materializes the world view (:class:`WorldAggregator`);
* three read surfaces on rank 0: a ``GET /metrics`` Prometheus-text
  endpoint (:class:`MetricsHTTPServer`, ``HOROVOD_TPU_METRICS_PORT``,
  stdlib http.server on a daemon thread), a periodic JSONL snapshot
  file (``HOROVOD_TPU_METRICS_LOG``), and the public
  ``horovod_tpu.metrics()`` API (common/basics.py).

Merge semantics (the world fold): counters sum; gauges sum or max per
their declared ``agg`` (peer heartbeat ages are ``max`` — the oldest
silence in the world is the alarming one); histograms add bucket-wise
(bounds must match — they are part of the metric's identity).

Metric names may carry Prometheus labels inline
(``hvd_ops_total{op="allreduce"}``): the full labeled string is the
registry key and the aggregation key, and the renderer splits it back
into name + label set (merging ``le=`` into existing labels for
histogram buckets).
"""

from __future__ import annotations

import json
import threading
import time

from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck
from bisect import bisect_left
from typing import Callable, Dict, List, Tuple

# Latency-shaped default buckets (seconds): negotiation rounds sit in
# the 100us-10ms band on a healthy host, collectives run up to seconds.
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# Ratio-shaped buckets (fusion-buffer fill, 0..1; the tail catches
# batches that overshoot the threshold by design — one tensor already
# over it ships alone).
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0, 1.5)

KIND_COUNTER = "c"
KIND_GAUGE = "g"
KIND_HISTOGRAM = "h"

AGG_SUM = "sum"
AGG_MAX = "max"


class _NoOpMetric:
    """Disabled metric: every hook is a cheap no-op. One shared
    instance stands in for every metric of every kind, so the
    disabled-path test can assert identity (`is NOOP_METRIC`) on each
    instrumented call site."""

    enabled = False

    def inc(self, v=1): pass
    def set(self, v): pass
    def set_total(self, v): pass
    def observe(self, v): pass


NOOP_METRIC = _NoOpMetric()


class Counter:
    """Monotonic counter. ``inc`` takes the metric's lock — increments
    may arrive from the background loop, finalizer threads and the
    timeline writer; a GIL-raced ``+=`` would silently lose counts.
    ``set_total`` overwrites the total (mirror counters whose true
    source is elsewhere, e.g. the response cache's hit count)."""

    __slots__ = ("name", "help", "_v", "_lock")
    enabled = True

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._v = 0.0
        self._lock = lockdep.lock("metrics.Counter._lock")

    def inc(self, v=1) -> None:
        with self._lock:
            self._v += v

    def set_total(self, v) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        return self._v

    def record(self) -> dict:
        rec = {"k": KIND_COUNTER, "v": self._v}
        if self.help:
            rec["help"] = self.help
        return rec


class Gauge:
    """Point-in-time value. ``set`` is a single attribute store
    (GIL-atomic); ``agg`` declares how the world fold combines ranks
    (queue depths sum, heartbeat ages max)."""

    __slots__ = ("name", "help", "agg", "_v")
    enabled = True

    def __init__(self, name: str, help: str = "", agg: str = AGG_SUM):
        if agg not in (AGG_SUM, AGG_MAX):
            raise ValueError(f"unknown gauge agg {agg!r}")
        self.name = name
        self.help = help
        self.agg = agg
        self._v = 0.0

    def set(self, v) -> None:
        self._v = float(v)

    @property
    def value(self) -> float:
        return self._v

    def record(self) -> dict:
        rec = {"k": KIND_GAUGE, "agg": self.agg, "v": self._v}
        if self.help:
            rec["help"] = self.help
        return rec


class Histogram:
    """Fixed-bucket histogram (+Inf bucket implicit at the end).
    ``observe`` is a bisect + two increments under the metric's lock;
    bounds are part of the metric's identity and must match across
    ranks for the world fold to add bucket-wise."""

    __slots__ = ("name", "help", "bounds", "_counts", "_sum", "_count",
                 "_lock")
    enabled = True

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError("histogram buckets must be strictly "
                             f"increasing; got {buckets}")
        self.name = name
        self.help = help
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = lockdep.lock("metrics.Histogram._lock")

    def observe(self, v) -> None:
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def record(self) -> dict:
        with self._lock:
            rec = {"k": KIND_HISTOGRAM, "bounds": list(self.bounds),
                   "counts": list(self._counts), "sum": self._sum,
                   "count": self._count}
        if self.help:
            rec["help"] = self.help
        return rec


class _NoOpRegistry:
    """Disabled registry: every factory returns the shared no-op
    metric and snapshots are empty. Collectors are dropped — with
    metrics off nothing ever reads them."""

    enabled = False

    def counter(self, name, help=""):
        return NOOP_METRIC

    def gauge(self, name, help="", agg=AGG_SUM):
        return NOOP_METRIC

    def histogram(self, name, help="", buckets=LATENCY_BUCKETS):
        return NOOP_METRIC

    def add_collector(self, fn):
        pass

    def snapshot(self) -> dict:
        return {}


NOOP_REGISTRY = _NoOpRegistry()


class MetricsRegistry:
    """Per-rank metric store. Factories are memoized by full (labeled)
    name, so two call sites asking for the same metric share one
    object; a kind mismatch on a reused name is a programming error
    and raises. ``add_collector`` registers a callback run at the top
    of every :meth:`snapshot` — the hook mirror-metrics use to pull
    values whose true source lives elsewhere (cache counters, queue
    depth, per-peer heartbeat ages) without touching the hot paths
    that maintain them."""

    enabled = True

    def __init__(self, const_labels: "Optional[Dict[str, str]]" = None):
        """``const_labels`` (e.g. ``{"tenant": "jobA"}``) are folded
        into every registered metric's labeled name, so a tenant
        sub-world's series stay distinct from the default world's and
        from other tenants' on every read surface (/metrics,
        hvd.metrics(), the control-tree world fold)."""
        self._lock = lockdep.lock("metrics.MetricsRegistry._lock")
        self._metrics: "Dict[str, object]" = {}
        self._collectors: List[Callable[[], None]] = []
        self._const_labels = dict(const_labels or {})

    def _labeled(self, name: str) -> str:
        if not self._const_labels:
            return name
        extra = ",".join(f'{k}="{v}"'
                         for k, v in sorted(self._const_labels.items()))
        base, labels = _split_labels(name)
        labels = f"{labels},{extra}" if labels else extra
        return f"{base}{{{labels}}}"

    def _get(self, name: str, factory, kind):
        name = self._labeled(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory(name)
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda n: Counter(n, help), Counter)

    def gauge(self, name: str, help: str = "",
              agg: str = AGG_SUM) -> Gauge:
        g = self._get(name, lambda n: Gauge(n, help, agg), Gauge)
        if g.agg != agg:
            # agg is part of the metric's identity (merge_into fails
            # loudly on it cross-rank) — the same must hold within a
            # rank, or a second call site silently folds wrong.
            raise ValueError(
                f"gauge {name!r} already registered with "
                f"agg={g.agg!r}, not {agg!r}")
        return g

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = LATENCY_BUCKETS
                  ) -> Histogram:
        h = self._get(name, lambda n: Histogram(n, help, buckets),
                      Histogram)
        if h.bounds != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} already registered with bounds "
                f"{h.bounds}, not {tuple(buckets)}")
        return h

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def snapshot(self) -> dict:
        """{labeled name: record} — a self-contained copy safe to
        merge, encode or render after the registry moves on."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            fn()
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: m.record() for name, m in metrics}


# -- merge semantics (the world fold) ---------------------------------------

def merge_into(dst: dict, src: dict) -> dict:
    """Fold snapshot ``src`` into ``dst`` in place (and return it):
    counters and histogram buckets add, gauges combine per their
    ``agg``. Mixed kinds or mismatched histogram bounds under one name
    mean the ranks disagree about the metric's identity — fail loudly
    rather than aggregate garbage."""
    for name, rec in src.items():
        cur = dst.get(name)
        if cur is None:
            dst[name] = {k: (list(v) if isinstance(v, list) else v)
                         for k, v in rec.items()}
            continue
        if cur["k"] != rec["k"]:
            raise ValueError(
                f"metric {name!r} kind mismatch across ranks: "
                f"{cur['k']!r} vs {rec['k']!r}")
        if rec["k"] == KIND_COUNTER:
            cur["v"] += rec["v"]
        elif rec["k"] == KIND_GAUGE:
            if cur.get("agg") != rec.get("agg"):
                raise ValueError(
                    f"gauge {name!r} agg mismatch across ranks")
            if rec.get("agg") == AGG_MAX:
                cur["v"] = max(cur["v"], rec["v"])
            else:
                cur["v"] += rec["v"]
        else:
            if list(cur["bounds"]) != list(rec["bounds"]):
                raise ValueError(
                    f"histogram {name!r} bucket bounds differ across "
                    f"ranks")
            cur["counts"] = [a + b for a, b in zip(cur["counts"],
                                                   rec["counts"])]
            cur["sum"] += rec["sum"]
            cur["count"] += rec["count"]
    return dst


# -- scaling-efficiency feed -------------------------------------------------
# Written by whoever measured a scaling run in THIS process — the
# MULTICHIP harness (__graft_entry__.run_multichip) after its
# per-world-size sweep, or an operator's own calibration pass. Any
# armed runtime registry mirrors the values lazily as the
# hvd_scaling_efficiency{world_size="N"} gauge family on its next
# snapshot (runtime._collect_runtime_metrics).

_scaling_eff: "Dict[int, float]" = {}


def note_scaling_efficiency(world_size: int, efficiency: float) -> None:
    _scaling_eff[int(world_size)] = float(efficiency)


def scaling_efficiencies() -> "Dict[int, float]":
    return dict(_scaling_eff)


# -- Prometheus text rendering ----------------------------------------------

def _split_labels(full_name: str) -> Tuple[str, str]:
    """'name{a="b"}' -> ('name', 'a="b"'); 'name' -> ('name', '')."""
    i = full_name.find("{")
    if i < 0:
        return full_name, ""
    return full_name[:i], full_name[i + 1:].rstrip("}")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


def render_prometheus(snap: dict) -> str:
    """Prometheus text exposition (version 0.0.4) of a snapshot.
    Histograms render the conventional ``_bucket{le=...}`` cumulative
    series plus ``_sum`` and ``_count``; inline labels on the metric
    name merge with the ``le`` label. ``# HELP`` renders when the
    record carries one (the wire codec drops help to keep frames
    compact, so world views document the metrics rank 0 also owns)."""
    lines: List[str] = []
    typed: set = set()
    for full_name in sorted(snap):
        rec = snap[full_name]
        base, labels = _split_labels(full_name)
        kind = rec["k"]
        if base not in typed:
            typed.add(base)
            help_text = rec.get("help")
            if help_text:
                lines.append(
                    f"# HELP {base} "
                    + help_text.replace("\\", r"\\").replace("\n",
                                                             r"\n"))
            ptype = {KIND_COUNTER: "counter", KIND_GAUGE: "gauge",
                     KIND_HISTOGRAM: "histogram"}[kind]
            lines.append(f"# TYPE {base} {ptype}")
        if kind in (KIND_COUNTER, KIND_GAUGE):
            suffix = f"{{{labels}}}" if labels else ""
            lines.append(f"{base}{suffix} {_fmt(rec['v'])}")
            continue
        cum = 0
        bounds = list(rec["bounds"]) + [float("inf")]
        for b, c in zip(bounds, rec["counts"]):
            cum += c
            le = f'le="{_fmt(b)}"'
            lab = f"{labels},{le}" if labels else le
            lines.append(f"{base}_bucket{{{lab}}} {cum}")
        suffix = f"{{{labels}}}" if labels else ""
        lines.append(f"{base}_sum{suffix} {_fmt(rec['sum'])}")
        lines.append(f"{base}_count{suffix} {rec['count']}")
    return "\n".join(lines) + "\n"


# -- world aggregation ------------------------------------------------------

class WorldAggregator:
    """Rank 0's fold point. The control plane delivers each owner
    channel's latest METRICS frame (a local root's frame already sums
    its whole host) through :meth:`ingest`; the local registry's
    snapshot arrives through :meth:`update_local`. :meth:`world`
    merges the latest view of every reporter — sums of totals, not
    deltas, so a dropped or reordered frame can never double-count.
    Thread-safe: ingest runs on the background loop, reads come from
    the HTTP server thread and the public API."""

    def __init__(self, size: int = 1):
        self._lock = lockdep.lock("metrics.WorldAggregator._lock")
        self._size = size
        self._local: dict = {}
        # owner rank -> (nranks represented, snapshot, recv time)
        self._owners: Dict[int, tuple] = {}
        # name -> identity (kind + agg/bounds): the O(metrics)
        # admission check for arriving frames. The local registry is
        # authoritative; accepted frames register the names it lacks.
        self._ident: Dict[str, tuple] = {}

    @staticmethod
    def _identity(rec: dict) -> tuple:
        k = rec["k"]
        if k == KIND_GAUGE:
            return (k, rec.get("agg", AGG_SUM))
        if k == KIND_HISTOGRAM:
            return (k, tuple(rec["bounds"]))
        return (k,)

    def _register_idents(self, snap: dict) -> None:
        for name, rec in snap.items():
            self._ident[name] = self._identity(rec)

    def update_local(self, snap: dict) -> None:
        with self._lock:
            self._local = snap
            self._register_idents(snap)

    def ingest(self, owner_rank: int, payload: bytes) -> None:
        from horovod_tpu.common import wire
        try:
            nranks, snap = wire.parse_metrics_frame(payload)
        except Exception:
            return  # a garbled best-effort frame is dropped, not fatal
        with self._lock:
            # Admission check against the persistent identity map —
            # O(metrics) per frame, NOT a re-merge of every stored
            # snapshot (ingest runs on the coordinator's negotiation
            # thread, inside the gather loop). A parseable frame whose
            # identities disagree (skewed code across ranks — a
            # kind/agg/bucket change mid-rolling-restart) is DROPPED,
            # never stored to poison later world() folds.
            for name, rec in snap.items():
                known = self._ident.get(name)
                if known is not None and known != self._identity(rec):
                    return
            self._register_idents(snap)
            self._owners[owner_rank] = (nranks, snap,
                                        time.monotonic())

    def local(self) -> dict:
        with self._lock:
            return dict(self._local)

    def world(self) -> dict:
        with self._lock:
            merged: dict = {}
            merge_into(merged, self._local)
            reporting = 1 if self._local else 0
            for nranks, snap, _ts in self._owners.values():
                # Belt to ingest's trial-merge braces: a frame that
                # stops merging (the LOCAL registry grew a conflicting
                # metric after the frame was admitted) is skipped
                # whole — folded into a scratch copy first so a
                # half-merged frame can never leak partial sums — and
                # the read surfaces never raise from the fold.
                try:
                    trial = merge_into({}, merged)
                    merge_into(trial, snap)
                except ValueError:
                    continue
                merged = trial
                reporting += nranks
            merged["hvd_ranks_reporting"] = {
                "k": KIND_GAUGE, "agg": AGG_SUM, "v": float(reporting)}
            merged["hvd_world_size"] = {
                "k": KIND_GAUGE, "agg": AGG_MAX, "v": float(self._size)}
            return merged


# -- rank-0 read surfaces ---------------------------------------------------

class MetricsHTTPServer:
    """``GET /metrics`` (Prometheus text) + ``GET /metrics.json`` on a
    stdlib ThreadingHTTPServer daemon thread. ``port=0`` binds an
    ephemeral port, reported via :attr:`port` (tests and the
    ``horovod_tpu.metrics()`` API read it)."""

    def __init__(self, world_fn: Callable[[], dict], port: int = 0,
                 host: str = ""):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler contract)
                threadcheck.register_role("hvd-metrics-http")
                try:
                    snap = world_fn()
                    if self.path.startswith("/metrics.json"):
                        body = json.dumps(snap).encode()
                        ctype = "application/json"
                    elif self.path.startswith("/metrics"):
                        body = render_prometheus(snap).encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # never kill the serving thread
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not log events
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="hvd-metrics-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass


class JsonlMetricsLog:
    """Appends one ``{"ts": ..., "world": {...}}`` line per publish
    interval — the offline twin of the HTTP endpoint for deployments
    without a scraper. Write failures disable the log (a full disk
    must not take the control plane down with it)."""

    def __init__(self, path: str):
        self._path = path
        self._dead = False

    def append(self, snap: dict) -> None:
        if self._dead:
            return
        try:
            with open(self._path, "a") as f:
                f.write(json.dumps({"ts": time.time(), "world": snap},
                                   separators=(",", ":")) + "\n")
        except OSError:
            self._dead = True


def create_registry(enabled: bool, tenant: str = ""):
    """The registry for one runtime: a real one when the metrics plane
    is on, the shared no-op otherwise — mirroring create_timeline.
    ``tenant`` labels every metric of a tenant sub-world's runtime
    (common/tenancy.py) so per-tenant bytes/cycles/queue-depth stay
    separable on every read surface."""
    if not enabled:
        return NOOP_REGISTRY
    return MetricsRegistry(
        const_labels={"tenant": tenant} if tenant else None)
