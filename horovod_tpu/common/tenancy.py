"""Multi-tenant collective service: many jobs, one warm fleet.

The substrate PRs built — sub-worlds (``init(comm=[ranks])``), elastic
membership (PR 8), the metrics/trace planes (PRs 4/11) and an overlap
runner that already interleaves independent cycles (PR 10) — meets its
consumer here: the fleet stops being one job's private runtime and
becomes a shared collective *service* (docs/multitenancy.md).

Three coupled pieces:

1. **Tenants** — :func:`create_tenant` turns a sub-world into a
   first-class tenant: its own Runtime + controller on a coordinator
   port derived from the FULL membership and tenant name (two tenants
   can never squat one port, unlike the old first-rank-only
   derivation), a nonzero ``world_id`` stamped on every control frame
   (``wire.stamp_world``) so a frame that strays across worlds fails
   fast naming both ids, and per-tenant labels on the metrics/trace
   planes. One process may be a member of several tenants at once —
   each tenant is an independent tensor table driven by its own
   background loop, so a coordinator process drives several tenants'
   negotiation cycles concurrently.

2. **QoS-weighted scheduling** — every process hosts one
   :class:`TenantScheduler`; each tenant runtime's cycle loop acquires
   its :class:`_Lane` before negotiating a cycle with local work.
   Lanes interleave by *stride scheduling* over a virtual clock
   (weight 3 gets 3 cycles per weight-1 tenant's 1 when both are
   saturated) and carry token-bucket byte/cycle quotas fed from the
   live PR 4 metrics when armed (the runtime's own negotiated-byte
   count otherwise). An over-quota or out-weighted tenant's cycle is
   DEFERRED — bounded far under the heartbeat deadline by the same
   hold rule as every other hold in the cycle loop — never dropped,
   so pacing can never corrupt a world. The weight/quota values
   themselves are world-replicated: the tenant coordinator broadcasts
   its descriptor in the controller handshake and every member
   installs it through the ``@world_coherent`` apply path, so all
   ranks of a tenant pace under ONE policy no matter their local env.

3. **Service mode** — ``hvdtpurun --service`` (HOROVOD_TPU_SERVICE)
   opens the :class:`ServiceGate` on the fleet's rank 0: a listener in
   the mold of the PR 8 elastic listener whose manifest-style frames
   (wire.TENANT_*) let jobs ATTACH to and DETACH from the warm fleet
   without any re-rendezvous of the fleet's own world. The flagship
   path is batch inference: the training loop publishes parameter
   snapshots (:func:`publish_snapshot`), and an attached replica group
   pulls them over a broadcast FANOUT — the gate sends one copy to the
   group's root, which relays down a binary tree of the group's own
   listeners, so serving N replicas costs the fleet one send.
"""

from __future__ import annotations

import atexit
import contextlib
import socket
import threading
import time
import zlib
from typing import Dict, List, Optional

from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network
from horovod_tpu.common import wire
from horovod_tpu.common.config import Config
from horovod_tpu.common.invariants import world_coherent

# Channel tag for the service gate's dedicated sockets (its own
# connection namespace, like elastic.RDZV_TAG on rendezvous sockets).
SERVICE_TAG = 9

# Derived-port spread for sub-world coordinators. Must comfortably
# exceed any realistic tenant count on one fleet while keeping
# base+offset a valid port.
_PORT_SPREAD = 8191


def derive_world_id(name: str, ranks) -> int:
    """Nonzero u32 identity of a (tenant, membership) pair — stamped
    on every control frame of the sub-world. Deterministic from
    arguments every member knows, so all ranks derive it identically
    with no extra negotiation."""
    key = f"{name}|{','.join(str(int(r)) for r in ranks)}"
    return 1 + (zlib.crc32(key.encode()) % 0xFFFFFFFE)


def derive_subworld_port(base_port: int, name: str, ranks) -> int:
    """Coordinator port for a sub-world, derived from the FULL
    membership and tenant name. The pre-tenancy derivation keyed on
    ``ranks[0]`` alone — two sub-worlds anchored at the same first
    rank (or a rank-0-anchored subset squatting the fleet's own env
    port) collided; worse, the collision handed one tenant's frames
    to another's coordinator. Now distinct (name, membership) pairs
    spread over ``_PORT_SPREAD`` ports and the world-id handshake
    check turns any residual collision into a named startup error
    instead of silent corruption."""
    key = f"{name}|{','.join(str(int(r)) for r in ranks)}"
    port = base_port + 1 + (zlib.crc32(key.encode()) % _PORT_SPREAD)
    if port > 65535:
        # High ephemeral base: fold back into the registered range,
        # still deterministic for every member, still != base.
        port = 1024 + ((port - 65536) % (65535 - 1024))
        if port == base_port:
            port += 1
    return port


# ---------------------------------------------------------------------------
# QoS-weighted tenant scheduling
# ---------------------------------------------------------------------------

class _Lane:
    """One tenant's seat in the process-local scheduler. All state is
    guarded by the scheduler's condition; the runtime's background
    thread is the only caller of acquire/note_cycle."""

    def __init__(self, sched: "TenantScheduler", world_id: int,
                 name: str, weight: float, quota_bytes_s: float,
                 quota_cycles_s: float, live_bytes_fn=None,
                 metrics=None):
        self._sched = sched
        self.world_id = world_id
        self.name = name
        self.weight = max(float(weight), 1e-6)
        self.quota_bytes_s = max(float(quota_bytes_s), 0.0)
        self.quota_cycles_s = max(float(quota_cycles_s), 0.0)
        # Token buckets: one second of burst capacity; note_cycle
        # charges AFTER the fact, so a bucket can go negative and the
        # next acquire waits out the deficit.
        self.tokens_b = self.quota_bytes_s
        self.tokens_c = self.quota_cycles_s
        self.refill_t = time.monotonic()
        # Stride scheduling over a shared virtual clock: each granted
        # cycle advances vtime by 1/weight; the wanting lane with the
        # smallest vtime goes next. ``last_done`` drives the
        # idle-credit reset (see TenantScheduler._acquire).
        self.vtime = 0.0
        self.want = False
        self.last_done = time.monotonic()
        # Live quota source (the PR 4 metrics plane): a callable
        # returning this tenant's cumulative wire-byte total; when
        # armed it overrides the runtime-reported per-cycle bytes.
        self._live_bytes_fn = live_bytes_fn
        self._live_bytes_seen: Optional[float] = None
        # Observability (no-op metric objects when the plane is off).
        self._m_deferrals = getattr(metrics, "deferrals", None)
        self._m_deferred_s = getattr(metrics, "deferred_s", None)
        self._m_cycles = getattr(metrics, "cycles", None)
        self.deferrals = 0
        self.deferred_s = 0.0
        self.cycles = 0
        self.bytes = 0

    # Called by Runtime._run_loop_once (see bind_tenant_lane).
    def acquire(self, max_hold_s: float) -> float:
        return self._sched._acquire(self, max_hold_s)

    def note_cycle(self, reported_bytes: int) -> None:
        nbytes = int(reported_bytes)
        if self._live_bytes_fn is not None:
            try:
                total = float(self._live_bytes_fn())
                if self._live_bytes_seen is not None:
                    nbytes = max(0, int(total - self._live_bytes_seen))
                self._live_bytes_seen = total
            except Exception:
                pass  # metrics plane mid-teardown: keep the report
        self._sched._note(self, nbytes)

    def status_line(self) -> str:
        return (f"weight {self.weight:g}, {self.cycles} cycles, "
                f"{self.bytes} B negotiated, {self.deferrals} "
                f"deferrals ({self.deferred_s:.2f}s deferred)")


class TenantScheduler:
    """Process-local arbiter interleaving concurrent tenants' cycles.

    Pacing is rank-local (like the burst/idle/overlap holds): every
    member of a tenant runs the same world-replicated weights and
    quotas, so their independent decisions agree to within one cycle,
    and a rank that defers simply delays the blocking gather — bounded
    far under the heartbeat deadline, it can never be mistaken for
    death or corrupt a frame."""

    # A lane quiet for longer than this re-enters at the top of the
    # virtual clock: no credit accrues while idle, so a freshly-busy
    # tenant cannot monopolize the fleet to "catch up" with one that
    # was running all along. Saturated lanes (sub-cycle gaps between
    # note_cycle and the next acquire) are NEVER reset — the stride
    # differential between their clocks IS the weighting mechanism.
    _IDLE_RESET_S = 0.25

    def __init__(self):
        self._cv = lockdep.condition("tenancy.TenantScheduler._lock")
        self._lanes: List[_Lane] = []

    def _vmax(self) -> float:
        return max((l.vtime for l in self._lanes), default=0.0)

    def register(self, world_id: int, name: str, weight: float,
                 quota_bytes_s: float, quota_cycles_s: float,
                 live_bytes_fn=None, metrics=None) -> _Lane:
        lane = _Lane(self, world_id, name, weight, quota_bytes_s,
                     quota_cycles_s, live_bytes_fn=live_bytes_fn,
                     metrics=metrics)
        with self._cv:
            # a newcomer starts at the top of the clock: no credit
            # for the time before it existed
            lane.vtime = self._vmax()
            self._lanes.append(lane)
        return lane

    def unregister(self, lane: _Lane) -> None:
        with self._cv:
            if lane in self._lanes:
                self._lanes.remove(lane)
            self._cv.notify_all()

    def lanes(self) -> List[_Lane]:
        with self._cv:
            return list(self._lanes)

    def _refill(self, lane: _Lane, now: float) -> None:
        dt = max(0.0, now - lane.refill_t)
        lane.refill_t = now
        if lane.quota_bytes_s:
            lane.tokens_b = min(lane.quota_bytes_s,
                                lane.tokens_b + dt * lane.quota_bytes_s)
        if lane.quota_cycles_s:
            lane.tokens_c = min(
                lane.quota_cycles_s,
                lane.tokens_c + dt * lane.quota_cycles_s)

    def _quota_wait(self, lane: _Lane) -> float:
        """Seconds until the lane's most-indebted bucket refills to
        non-negative; 0 when within quota."""
        wait = 0.0
        if lane.quota_bytes_s and lane.tokens_b < 0:
            wait = max(wait, -lane.tokens_b / lane.quota_bytes_s)
        if lane.quota_cycles_s and lane.tokens_c < 0:
            wait = max(wait, -lane.tokens_c / lane.quota_cycles_s)
        return wait

    def _solvent_at(self, lane: _Lane, now: float) -> bool:
        """Would ``lane``'s buckets be non-negative at ``now``?
        Projected WITHOUT mutating (refills are lazy, applied by each
        lane's own acquire) — used to exclude quota-parked lanes from
        the weighted-interleave contention check: a lane that CANNOT
        run must never defer one that can (priority inversion — the
        unlimited co-tenant of a tightly-capped tenant would otherwise
        crawl at the capped tenant's pace)."""
        dt = max(0.0, now - lane.refill_t)
        if lane.quota_bytes_s and \
                lane.tokens_b + dt * lane.quota_bytes_s < 0:
            return False
        if lane.quota_cycles_s and \
                lane.tokens_c + dt * lane.quota_cycles_s < 0:
            return False
        return True

    def _acquire(self, lane: _Lane, max_hold_s: float) -> float:
        """Block until it is ``lane``'s turn (weighted interleave) and
        its quota buckets are solvent, or until ``max_hold_s`` passes
        — the cycle then proceeds regardless (deferred, never lost).
        Returns the seconds spent deferred."""
        t0 = time.monotonic()
        deadline = t0 + max(0.0, max_hold_s)
        deferred = 0.0
        with self._cv:
            # ``want`` marks the lane's whole BUSY period — from here
            # until note_cycle reports the cycle done — not just this
            # wait. A lane merely mid-cycle still counts as a
            # contender, or back-to-back fast cycles would never
            # overlap another lane's wait window and weights could
            # not bite.
            lane.want = True
            if t0 - lane.last_done > self._IDLE_RESET_S:
                lane.vtime = max(lane.vtime, self._vmax())
            try:
                while True:
                    now = time.monotonic()
                    self._refill(lane, now)
                    if now >= deadline:
                        break
                    wait = self._quota_wait(lane)
                    if wait <= 0.0:
                        contender = any(
                            o.want and o.vtime < lane.vtime - 1e-12
                            and self._solvent_at(o, now)
                            for o in self._lanes if o is not lane)
                        if not contender:
                            break
                        # Out-weighted: wait for a competitor's grant
                        # to move the clock (notify below), re-check
                        # at least every 50 ms in case it went idle.
                        wait = 0.05
                    self._cv.wait(min(wait, deadline - now))
            finally:
                now = time.monotonic()
                deferred = now - t0
                # Charge the granted cycle to the virtual clock.
                lane.vtime += 1.0 / lane.weight
                if lane.quota_cycles_s:
                    lane.tokens_c -= 1.0
                if deferred > 0.001:
                    lane.deferrals += 1
                    lane.deferred_s += deferred
                    if lane._m_deferrals is not None:
                        lane._m_deferrals.inc()
                        lane._m_deferred_s.inc(deferred)
                self._cv.notify_all()
        return deferred

    def _note(self, lane: _Lane, nbytes: int) -> None:
        with self._cv:
            lane.want = False  # busy period over (see _acquire)
            lane.last_done = time.monotonic()
            lane.cycles += 1
            lane.bytes += nbytes
            if lane._m_cycles is not None:
                lane._m_cycles.inc()
            if lane.quota_bytes_s:
                lane.tokens_b -= nbytes
            self._cv.notify_all()


_SCHEDULER: Optional[TenantScheduler] = None
_SCHED_LOCK = lockdep.lock("tenancy._SCHED_LOCK")


def scheduler() -> TenantScheduler:
    """The process-wide tenant scheduler (created on first use)."""
    global _SCHEDULER
    if _SCHEDULER is None:
        with _SCHED_LOCK:
            if _SCHEDULER is None:
                _SCHEDULER = TenantScheduler()
    return _SCHEDULER


# ---------------------------------------------------------------------------
# Tenants
# ---------------------------------------------------------------------------

class _LaneMetrics:
    """Per-tenant scheduler metrics on the tenant runtime's registry
    (no-op objects when the plane is off — the NOOP_METRIC pattern)."""

    def __init__(self, registry):
        self.deferrals = registry.counter(
            "hvd_tenant_deferrals_total",
            "cycles of this tenant the QoS scheduler deferred")
        self.deferred_s = registry.counter(
            "hvd_tenant_deferred_seconds_total",
            "total time this tenant's cycles spent deferred")
        self.cycles = registry.counter(
            "hvd_tenant_cycles_total",
            "negotiation cycles this tenant completed with local work")


class Tenant:
    """One job's seat on the shared fleet: an independent runtime over
    a sub-world, scheduled against its co-tenants. Collective methods
    mirror the top-level ops API and route to THIS tenant's runtime."""

    def __init__(self, name: str, cfg: Config, runtime):
        self.name = name
        self.world_id = cfg.world_id
        self._cfg = cfg
        self._runtime = runtime
        self._lane: Optional[_Lane] = None
        # The world-replicated scheduling descriptor: weight/quotas
        # every member paces under. Installed ONLY from the
        # coordinator's handshake broadcast (_apply_descriptor) — a
        # rank-local env value never reaches the scheduler directly.
        self._desc: Optional[dict] = None  # hvdlint: world-replicated

    @world_coherent
    def _apply_descriptor(self, desc: dict) -> None:
        """Install the coordinator-broadcast weight/quota descriptor —
        world-identical input by construction (every member decodes
        the same handshake blob), so tenant scheduling state can
        never diverge across ranks."""
        self._desc = dict(desc)

    def _bind_lane(self) -> None:
        desc = self._desc or {}
        reg = self._runtime.metrics
        live_fn = None
        if getattr(reg, "enabled", False):
            # Quota enforcement from the LIVE metrics plane: the same
            # counters /metrics and hvd.metrics() expose. The counter
            # objects are memoized by name, so these are the very
            # instances the data plane increments.
            counters = [reg.counter(n) for n in (
                "hvd_bytes_allreduced_total",
                "hvd_bytes_allgathered_total",
                "hvd_bytes_broadcast_total",
                "hvd_bytes_alltoall_total",
                "hvd_bytes_reducescattered_total")]
            live_fn = lambda: sum(c.value() for c in counters)
        reg.gauge("hvd_tenant_weight",
                  "QoS weight of this tenant (world-replicated)"
                  ).set(desc.get("weight", 1.0))
        self._lane = scheduler().register(
            self.world_id, self.name,
            desc.get("weight", 1.0),
            desc.get("quota_bytes_s", 0.0),
            desc.get("quota_cycles_s", 0.0),
            live_bytes_fn=live_fn,
            metrics=_LaneMetrics(reg))
        self._runtime.bind_tenant_lane(self._lane)

    # -- identity --------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._runtime.controller.topology.rank

    @property
    def size(self) -> int:
        return self._runtime.controller.topology.size

    @property
    def alive(self) -> bool:
        rt = self._runtime
        return rt is not None and rt.alive

    def lane_stats(self) -> dict:
        lane = self._lane
        if lane is None:
            return {}
        return {"cycles": lane.cycles, "bytes": lane.bytes,
                "deferrals": lane.deferrals,
                "deferred_s": lane.deferred_s,
                "weight": lane.weight}

    def metrics(self) -> dict:
        return self._runtime.metrics_view()

    # -- op routing ------------------------------------------------------
    @contextlib.contextmanager
    def use(self):
        """Route the module-level ops API (hvd.allreduce, ...) to this
        tenant's runtime within the block — the mechanism behind every
        collective method below."""
        from horovod_tpu.common import basics
        token = basics._active_runtime.set(self._runtime)
        try:
            yield self
        finally:
            basics._active_runtime.reset(token)

    def _op(self, fname, *args, **kwargs):
        from horovod_tpu import ops as hops
        with self.use():
            return getattr(hops, fname)(*args, **kwargs)

    def allreduce(self, *a, **kw): return self._op("allreduce", *a, **kw)
    def allreduce_async(self, *a, **kw):
        return self._op("allreduce_async", *a, **kw)
    def grouped_allreduce(self, *a, **kw):
        return self._op("grouped_allreduce", *a, **kw)
    def grouped_allreduce_async(self, *a, **kw):
        return self._op("grouped_allreduce_async", *a, **kw)
    def allgather(self, *a, **kw): return self._op("allgather", *a, **kw)
    def allgather_async(self, *a, **kw):
        return self._op("allgather_async", *a, **kw)
    def broadcast(self, *a, **kw): return self._op("broadcast", *a, **kw)
    def broadcast_async(self, *a, **kw):
        return self._op("broadcast_async", *a, **kw)
    def alltoall(self, *a, **kw): return self._op("alltoall", *a, **kw)
    def reducescatter(self, *a, **kw):
        return self._op("reducescatter", *a, **kw)
    def barrier(self, *a, **kw): return self._op("barrier", *a, **kw)
    def poll(self, handle): return self._op("poll", handle)
    def synchronize(self, handle): return self._op("synchronize", handle)

    # -- lifecycle -------------------------------------------------------
    def shutdown(self) -> None:
        rt, self._runtime = self._runtime, None
        if rt is None:
            return
        rt.request_shutdown()
        rt.join(timeout=30.0)
        if self._lane is not None:
            scheduler().unregister(self._lane)
            self._lane = None
        from horovod_tpu import ops as _ops
        _ops.reset_name_counters(self.name)
        with _TENANTS_LOCK:
            _TENANTS.pop(self.name, None)


@world_coherent
def _install_descriptor(tenant: Tenant, desc: dict) -> None:
    """Install the tenant's scheduling descriptor — world-identical
    input by construction: members decode the coordinator's handshake
    blob, and the coordinator installs the very values it broadcast
    (hvdlint's world-coherence analyzer anchors the chain here)."""
    tenant._apply_descriptor(desc)


_TENANTS: Dict[str, Tenant] = {}
_TENANTS_LOCK = lockdep.lock("tenancy._TENANTS_LOCK")


def create_tenant(name: str, comm, weight: Optional[float] = None,
                  quota_bytes_s: Optional[float] = None,
                  quota_cycles_s: Optional[float] = None,
                  config: Optional[Config] = None) -> Optional[Tenant]:
    """Bring up tenant ``name`` over the global ranks in ``comm``.

    Every member process calls this with the SAME (name, comm);
    non-members get ``None`` back and are untouched (unlike
    ``init(comm=...)``, which gives abstainers a size-1 world — a
    tenant is opt-in). Weight and quotas may be set per call or via
    HOROVOD_TENANT_WEIGHT / HOROVOD_TENANT_QUOTA_BYTES /
    HOROVOD_TENANT_QUOTA_CYCLES; whatever the tenant COORDINATOR
    resolves is broadcast in the handshake and wins on every member
    (world-replicated scheduling state)."""
    from horovod_tpu.common import basics
    ranks = [int(r) for r in comm]
    if not ranks:
        raise ValueError("a tenant needs at least one member rank")
    cfg = config or Config.from_env()
    g_rank = cfg.rank if cfg.rank >= 0 else 0
    if g_rank not in ranks:
        return None
    with _TENANTS_LOCK:
        if name in _TENANTS:
            raise ValueError(
                f"tenant {name!r} already exists in this process")
    if weight is not None:
        cfg.tenant_weight = float(weight)
    if quota_bytes_s is not None:
        cfg.tenant_quota_bytes_s = float(quota_bytes_s)
    if quota_cycles_s is not None:
        cfg.tenant_quota_cycles_s = float(quota_cycles_s)
    cfg.tenant_name = name
    cfg.world_id = derive_world_id(name, ranks)
    cfg.rank = ranks.index(g_rank)
    cfg.size = len(ranks)
    if cfg.controller_port:
        cfg.controller_port = derive_subworld_port(
            cfg.controller_port, name, ranks)
    # The launcher's reserved listener fd serves the DEFAULT world's
    # endpoint; a tenant coordinator always binds its derived port.
    cfg.controller_fd = -1
    # Tenants ride the fleet's warm processes; elastic re-rendezvous
    # belongs to the default world that owns those processes.
    cfg.elastic_enabled = False
    cfg.elastic_join = False
    # Fresh auto-name counters for this tenant's scope: a re-created
    # same-name tenant (or one whose member process was respawned)
    # must start its <op>.noname.<n> sequence at 0 on EVERY rank, or
    # surviving ranks' stale counters would diverge tensor names and
    # stall the new world.
    from horovod_tpu import ops as _ops
    _ops.reset_name_counters(name)
    rt = basics._build_runtime(cfg)
    tenant = Tenant(name, cfg, rt)
    desc = getattr(rt.controller, "tenant_desc", None)
    if desc is None:
        # Tenant coordinator (or a 1-member tenant): its own resolved
        # values ARE the broadcast descriptor.
        desc = descriptor_of(cfg)
    _install_descriptor(tenant, desc)
    tenant._bind_lane()
    with _TENANTS_LOCK:
        _TENANTS[name] = tenant
    hlog.debug(f"tenant {name!r} up: rank {tenant.rank} of "
               f"{tenant.size}, world {cfg.world_id:#010x}",
               rank=tenant.rank)
    return tenant


def descriptor_of(cfg: Config) -> dict:
    """The world-replicated scheduling descriptor the tenant
    coordinator broadcasts in its controller handshake."""
    return {"name": cfg.tenant_name,
            "world_id": cfg.world_id,
            "weight": cfg.tenant_weight,
            "quota_bytes_s": cfg.tenant_quota_bytes_s,
            "quota_cycles_s": cfg.tenant_quota_cycles_s}


def tenants() -> Dict[str, Tenant]:
    with _TENANTS_LOCK:
        return dict(_TENANTS)


def _shutdown_all() -> None:
    for t in list(tenants().values()):
        try:
            t.shutdown()
        except Exception:
            pass
    stop_service_gate()


# Registered AFTER basics registers its atexit(shutdown), so tenants
# (and the service gate) tear down BEFORE the default world does.
atexit.register(_shutdown_all)


# ---------------------------------------------------------------------------
# Service mode: attach / detach / snapshot fanout
# ---------------------------------------------------------------------------

_SNAPSHOT_POLL_S = 0.25


class ServiceGate:
    """Rank 0's attach point for service-mode jobs (hvdtpurun
    --service). Accepts TENANT_ATTACH manifests on a dedicated
    listener — the service-plane sibling of the PR 8 elastic listener,
    same Channel framing and manifest-shaped codecs — leases each
    replica group a member table once the group is complete, serves
    published parameter snapshots to group ROOTS (one send per group;
    the group fans out among itself), and lets replicas detach with an
    ACK. The fleet's own world never re-rendezvouses: everything here
    rides daemon threads beside the training loop."""

    def __init__(self, port: int = 0, secret: bytes = b""):
        self._secret = secret
        self._server = network.listen(port)
        self.port = self._server.getsockname()[1]
        self._cv = lockdep.condition("tenancy.ServiceGate._lock")
        self._closing = False
        # tenant name -> {"group": n, "members": {replica: (host, port)},
        #                 "chans": {replica: Channel}, "lease": id}
        self._groups: Dict[str, dict] = {}
        self._lease_seq = 0
        self._snapshot: Optional[bytes] = None
        self._snapshot_version = 0
        self.attaches = 0
        self.detaches = 0
        self.snapshots_served = 0
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="hvd-service-gate",
            daemon=True)
        self._accept_thread.start()

    # -- publishing ------------------------------------------------------
    def publish(self, params: Dict, version: Optional[int] = None
                ) -> int:
        """Store the latest parameter snapshot (serialized once, so N
        attached groups share one encoding). Returns the version."""
        with self._cv:
            v = version if version is not None \
                else self._snapshot_version + 1
            self._snapshot = wire.serialize_tenant_snapshot(v, params)
            self._snapshot_version = v
            self._cv.notify_all()
            return v

    # -- accept / per-replica service ------------------------------------
    def _accept_loop(self) -> None:
        threadcheck.register_role("hvd-service-gate")
        self._server.settimeout(0.5)
        while not self._closing:
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_replica,
                                 args=(sock,), daemon=True)
            t.start()
            # prune finished servers so a long-lived gate (the whole
            # point of service mode) never grows this list unboundedly
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_replica(self, sock) -> None:
        threadcheck.register_role("serve_replica")
        ch = None
        tenant = replica = None
        try:
            sock.settimeout(10.0)
            ch = network.Channel(sock, self._secret)
            tag, payload = ch.recv()
            if tag != SERVICE_TAG:
                raise ConnectionError(f"unexpected tag {tag}")
            m = wire.parse_tenant_attach(payload)
            if m["kind"] != wire.TENANT_ATTACH:
                raise ConnectionError(
                    f"expected attach, got kind {m['kind']}")
            tenant, replica = m["tenant"], m["replica"]
            group = max(1, m["group"])
            # The dialer's observed address overrides the self-report,
            # exactly like the elastic manifest path: it is what this
            # host can provably route back to.
            host = sock.getpeername()[0] or m["host"]
            with self._cv:
                g = self._groups.setdefault(
                    tenant, {"group": group, "members": {},
                             "chans": {}, "lease": 0})
                g["group"] = group
                g["members"][replica] = (host, m["port"])
                g["chans"][replica] = ch
                self.attaches += 1
                complete = len(g["members"]) >= g["group"]
                if complete and not g["lease"]:
                    self._lease_seq += 1
                    g["lease"] = self._lease_seq
                if complete:
                    self._cv.notify_all()
                else:
                    # Park until the group completes (or the gate
                    # closes) — the lease must carry the full member
                    # table for the fanout tree.
                    while (len(g["members"]) < g["group"]
                           and not self._closing):
                        self._cv.wait(0.5)
                members = [g["members"][i]
                           for i in sorted(g["members"])]
                lease = g["lease"]
            from horovod_tpu.common import elastic as _elastic
            sock.settimeout(None)
            ch.send(wire.serialize_tenant_lease(
                wire.TENANT_LEASE, 0, _elastic.generation(), lease,
                len(members), members), SERVICE_TAG)
            while True:
                tag, payload = ch.recv()
                if tag != SERVICE_TAG:
                    raise ConnectionError(f"unexpected tag {tag}")
                kind = payload[0] if payload else None
                if kind == wire.TENANT_DETACH:
                    with self._cv:
                        self.detaches += 1
                        g = self._groups.get(tenant)
                        if g is not None:
                            g["members"].pop(replica, None)
                            g["chans"].pop(replica, None)
                            if not g["members"]:
                                self._groups.pop(tenant, None)
                    ch.send(wire.serialize_tenant_lease(
                        wire.TENANT_ACK, 0, 0, lease, 0, []),
                        SERVICE_TAG)
                    return
                if kind != wire.TENANT_SNAPSHOT_REQ:
                    raise ConnectionError(
                        f"unexpected service frame kind {kind}")
                req = wire.parse_tenant_attach(payload)
                min_version = max(0, req["replica"])  # field reuse
                with self._cv:
                    while (self._snapshot is None
                           or self._snapshot_version < min_version) \
                            and not self._closing:
                        self._cv.wait(_SNAPSHOT_POLL_S)
                    snap = self._snapshot
                    self.snapshots_served += 1
                if snap is None:
                    raise ConnectionError("gate closed")
                ch.send(snap, SERVICE_TAG)
        except (ConnectionError, OSError, ValueError) as e:
            hlog.debug(f"service replica connection ended: {e}")
        finally:
            if ch is not None:
                try:
                    ch.close()
                except OSError:
                    pass

    def stats(self) -> dict:
        with self._cv:
            return {"attaches": self.attaches,
                    "detaches": self.detaches,
                    "snapshots_served": self.snapshots_served,
                    "groups": {t: len(g["members"])
                               for t, g in self._groups.items()},
                    "snapshot_version": self._snapshot_version}

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()
            # closing only the listener would leave every connected
            # replica's service thread parked in a timeout-less recv
            # until process exit — close their channels so those
            # threads unblock and drain
            chans = [ch for g in self._groups.values()
                     for ch in g["chans"].values()]
        for ch in chans:
            try:
                ch.close()
            except OSError:
                pass
        try:
            self._server.close()
        except OSError:
            pass


_GATE: Optional[ServiceGate] = None
_GATE_LOCK = lockdep.lock("tenancy._GATE_LOCK")


def start_service_gate(cfg: Config, secret: bytes) -> ServiceGate:
    """Open the service gate (init() calls this on the default world's
    rank 0 when HOROVOD_TPU_SERVICE is set). Idempotent."""
    global _GATE
    with _GATE_LOCK:
        if _GATE is None:
            _GATE = ServiceGate(cfg.service_port, secret)
            hlog.info(f"service gate listening on port {_GATE.port}",
                      rank=0)
    return _GATE


def service_gate() -> Optional[ServiceGate]:
    return _GATE


def stop_service_gate() -> None:
    global _GATE
    with _GATE_LOCK:
        gate, _GATE = _GATE, None
    if gate is not None:
        gate.close()


def publish_snapshot(params: Dict, version: Optional[int] = None
                     ) -> int:
    """Publish the current parameter snapshot to attached service-mode
    replica groups (rank 0 of a --service fleet; raises elsewhere)."""
    gate = _GATE
    if gate is None:
        raise ValueError(
            "no service gate is running — launch with hvdtpurun "
            "--service (HOROVOD_TPU_SERVICE=1) and publish from "
            "rank 0")
    return gate.publish(params, version)


class AttachedReplica:
    """A service-mode job's handle on the warm fleet: one replica of
    an attached group. ``fetch_snapshot`` pulls the latest published
    parameters — the group ROOT pulls from the gate, every replica
    relays to its binary-tree children, so the fleet pays one send per
    group regardless of group size."""

    def __init__(self, addr: str, port: int, tenant: str,
                 replica: int, group: int, secret: bytes = b"",
                 timeout: float = 30.0):
        self.tenant = tenant
        self.replica = int(replica)
        self.group = max(1, int(group))
        self._secret = secret
        # Fanout listener first: the lease's member table must carry a
        # live endpoint before the gate hands it to our parent.
        self._listener = network.listen(0)
        self._listener.settimeout(timeout)
        self.fanout_port = self._listener.getsockname()[1]
        self._ch = network.connect(addr, port, secret, timeout=timeout,
                                   retry_deadline=timeout)
        self._ch.send(wire.serialize_tenant_attach(
            wire.TENANT_ATTACH, 0, 0, tenant, self.replica,
            self.group, "127.0.0.1", self.fanout_port), SERVICE_TAG)
        try:
            tag, payload = self._ch.recv()
        except ConnectionError as e:
            # The gate rejects a bad first frame by closing — the
            # usual cause is a secret mismatch (the service plane
            # shares the fleet's HMAC auth boundary).
            raise ConnectionError(
                f"service gate at {addr}:{port} closed the attach "
                f"handshake: {e} — does this job present the fleet's "
                f"HOROVOD_SECRET_KEY?") from e
        if tag != SERVICE_TAG:
            raise ConnectionError(f"unexpected tag {tag}")
        lease = wire.parse_tenant_lease(payload)
        if lease["kind"] != wire.TENANT_LEASE:
            raise ConnectionError(
                f"attach refused (kind {lease['kind']})")
        self.lease = lease["lease"]
        self.generation = lease["gen"]
        self.members = lease["members"]

    def _children(self) -> List[int]:
        kids = [2 * self.replica + 1, 2 * self.replica + 2]
        return [k for k in kids if k < len(self.members)]

    def fetch_snapshot(self, min_version: int = 0,
                       timeout: float = 60.0):
        """-> (version, {name: numpy array}). Root: request + receive
        from the gate; children: receive the relayed frame from their
        tree parent. Every replica relays onward — children connect
        FIRST so the native cut-through (hvd_relay_frame, the same
        chunked relay the hierarchical data plane rides) can stream
        each chunk downstream while it is still arriving; deep trees
        then pay one frame time plus depth chunk times instead of
        depth frame times. Wire byte-identical to the classic
        recv-then-send leg, which remains the fallback."""
        kid_chs: List = []
        try:
            for kid in self._children():
                host, port = self.members[kid]
                kid_chs.append(network.connect(
                    host, port, self._secret, timeout=timeout,
                    retry_deadline=timeout))
            src_owned = None
            if self.replica == 0:
                self._ch.send(wire.serialize_tenant_attach(
                    wire.TENANT_SNAPSHOT_REQ, 0, 0, self.tenant,
                    int(min_version), self.group, "", 0), SERVICE_TAG)
                src = self._ch
            else:
                self._listener.settimeout(timeout)
                sock, _ = self._listener.accept()
                sock.settimeout(timeout)
                src_owned = network.Channel(sock, self._secret)
                src = src_owned
            try:
                frame = self._relay_recv(src, kid_chs, timeout)
                if frame is None:  # classic store-and-forward
                    tag, frame = src.recv()
                    if tag != SERVICE_TAG:
                        raise ConnectionError(f"unexpected tag {tag}")
                    for kid_ch in kid_chs:
                        kid_ch.send(frame, SERVICE_TAG)
            finally:
                if src_owned is not None:
                    src_owned.close()
        finally:
            for kid_ch in kid_chs:
                try:
                    kid_ch.close()
                except OSError:
                    pass
        return wire.parse_tenant_snapshot(frame)

    # Cut-through chunk size — matches the hierarchical data plane's
    # (common/controller.py _RELAY_CHUNK_BYTES rationale).
    _RELAY_CHUNK_BYTES = 256 * 1024
    _RELAY_BUF_BYTES = 1 << 20

    def _relay_recv(self, src, kid_chs, timeout: float):
        """One SERVICE_TAG frame from ``src`` streamed to the
        pre-connected children chunk-by-chunk (hvd_relay_frame).
        Returns the payload bytes, or None when the native relay
        cannot run (no lib / stale pre-reactor .so) — the caller then
        takes the classic leg. A non-SERVICE_TAG frame is a protocol
        error on this plane, relayed or not."""
        from horovod_tpu import native as _native
        lib = _native.get()
        if lib is None or not hasattr(lib, "hvd_relay_frame"):
            return None
        import ctypes as ct
        try:
            src_fd = src.sock.fileno()
            fds = [ch.sock.fileno() for ch in kid_chs]
        except OSError:
            return None
        kid_fds = (ct.c_int * max(1, len(fds)))(*(fds or [-1]))
        buf = bytearray(self._RELAY_BUF_BYTES)
        win = (ct.c_uint8 * len(buf)).from_buffer(buf)
        secret = self._secret or b""
        sbuf = (ct.c_uint8 * max(1, len(secret))).from_buffer_copy(
            secret or b"\x00")
        out_len = ct.c_int64(0)
        out_tag = ct.c_uint8(0)
        spill = ct.POINTER(ct.c_uint8)()
        rc = lib.hvd_relay_frame(
            src_fd, kid_fds, len(fds), SERVICE_TAG,
            ct.addressof(win), len(buf), sbuf, len(secret),
            None, 0, self._RELAY_CHUNK_BYTES,
            max(1, int(timeout * 1000)), -1,
            ct.byref(out_len), ct.byref(out_tag), ct.byref(spill))
        if rc == 2:
            # Deviation (absorbed, not relayed): free the bounce and
            # fail exactly like the classic leg's tag check.
            if spill:
                lib.hvd_free(spill)
            raise ConnectionError(f"unexpected tag {out_tag.value}")
        if rc == 1:  # relayed, payload spilled past the buffer
            payload = ct.string_at(spill, out_len.value)
            lib.hvd_free(spill)
            return payload
        if rc == 0:
            return bytes(buf[:out_len.value])
        raise ConnectionError(
            f"snapshot relay failed: errno {-rc}")

    def detach(self) -> None:
        """Leave the service plane; the fleet never notices beyond the
        gate's bookkeeping (no re-rendezvous, no world event)."""
        try:
            self._ch.send(wire.serialize_tenant_attach(
                wire.TENANT_DETACH, 0, 0, self.tenant, self.replica,
                self.group, "", 0), SERVICE_TAG)
            tag, payload = self._ch.recv()
            if tag != SERVICE_TAG or not payload \
                    or payload[0] != wire.TENANT_ACK:
                raise ConnectionError("detach not acknowledged")
        finally:
            try:
                self._ch.close()
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass


def attach(addr: str, port: int, tenant: str, replica: int = 0,
           group: int = 1, secret: bytes = b"",
           timeout: float = 30.0) -> AttachedReplica:
    """Attach a service-mode job replica to a warm --service fleet."""
    return AttachedReplica(addr, port, tenant, replica, group,
                           secret=secret, timeout=timeout)
