"""Gradient compression algorithms used during allreduce.

(reference: horovod/tensorflow/compression.py:1-74 and the identically
shaped horovod/torch/compression.py). The reference offers none/fp16;
on TPU the natural wire type is bfloat16 — same byte savings as fp16 but
with float32's exponent range, so no loss-scaling is needed — so we add
``Compression.bf16`` and make it the recommended choice.

Works on anything with a ``dtype`` and ``astype`` (numpy or jax arrays).
"""

from __future__ import annotations

import numpy as np


def _astype(tensor, dtype):
    # jax arrays and numpy arrays both have .astype; jax inside jit too.
    return tensor.astype(dtype)


def _is_floating(tensor) -> bool:
    d = np.dtype(tensor.dtype) if not hasattr(tensor.dtype, "name") \
        else tensor.dtype
    name = getattr(d, "name", str(d))
    return name in ("float16", "float32", "float64", "bfloat16")


class Compressor:
    """Interface to compress and decompress a tensor
    (reference: compression.py:22-33)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (reference: compression.py:36-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    _wire_dtype: str = "float16"

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if _is_floating(tensor):
            if cls._wire_dtype == "bfloat16":
                import ml_dtypes
                wire = np.dtype(ml_dtypes.bfloat16)
            else:
                wire = np.dtype(cls._wire_dtype)
            tensor = _astype(tensor, wire)
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and _is_floating(tensor):
            tensor = _astype(tensor, ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast to float16 on the wire (reference: compression.py:46-64)."""
    _wire_dtype = "float16"


class BF16Compressor(_CastCompressor):
    """Cast to bfloat16 on the wire — TPU-native extension; bf16 is the
    MXU/ICI-preferred reduced-precision type."""
    _wire_dtype = "bfloat16"


class Compression:
    """Option enum-alike (reference: compression.py:67-73)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
