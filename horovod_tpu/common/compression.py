"""Gradient compression algorithms used during allreduce.

(reference: horovod/tensorflow/compression.py:1-74 and the identically
shaped horovod/torch/compression.py). The reference offers none/fp16;
on TPU the natural wire type is bfloat16 — same byte savings as fp16 but
with float32's exponent range, so no loss-scaling is needed — so we add
``Compression.bf16`` and make it the recommended choice.

Works on anything with a ``dtype`` and ``astype`` (numpy or jax arrays).
"""

from __future__ import annotations

import numpy as np


def _astype(tensor, dtype):
    # jax arrays and numpy arrays both have .astype; jax inside jit too.
    return tensor.astype(dtype)


def _is_floating(tensor) -> bool:
    # ONE shared dtype table with the wire codec (common/wire_dtype.py):
    # the old per-module name list here silently missed extension
    # dtypes the other half knew about (jax's bfloat16 reaches numpy
    # as an ml_dtypes dtype whose .name the wire codec recognized but
    # a stale copy of this list would not).
    from horovod_tpu.common import wire_dtype as _wd
    return _wd.is_floating(tensor.dtype)


class Compressor:
    """Interface to compress and decompress a tensor
    (reference: compression.py:22-33)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (reference: compression.py:36-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


_warned_double_cast = False


class _CastCompressor(Compressor):
    _wire_dtype: str = "float16"

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        from horovod_tpu.common import wire_dtype as _wd
        if _wd.active() != _wd.WIRE_NONE:
            # The negotiated data plane already compresses on the wire
            # (HOROVOD_COMPRESSION): a framework-level cast on top
            # would quantize twice and decompress once. Deprecated
            # no-op in that configuration — warn once and pass
            # through. NOTE: this latch follows the LOCAL knob (the
            # world verdict is only known per batch, after
            # negotiation), so when combining the framework-level
            # compressor with HOROVOD_COMPRESSION the knob must be
            # set IDENTICALLY on every rank — a rank without it would
            # keep casting here and submit a different dtype, which
            # negotiation rejects loudly (mismatched data types).
            global _warned_double_cast
            if not _warned_double_cast:
                _warned_double_cast = True
                from horovod_tpu.common import logging as hlog
                hlog.warning(
                    "Compression.fp16/bf16 is a pass-through while "
                    "HOROVOD_COMPRESSION="
                    f"{_wd.WIRE_NAMES[_wd.active()]} is set: the "
                    "negotiated data plane compresses on the wire "
                    "instead (to the world's least aggressive "
                    "proposal — set the knob on EVERY rank, or a "
                    "mixed world degrades to uncompressed); drop "
                    "the framework-level compressor")
            return tensor, None
        if _is_floating(tensor):
            if cls._wire_dtype == "bfloat16":
                import ml_dtypes
                wire = np.dtype(ml_dtypes.bfloat16)
            else:
                wire = np.dtype(cls._wire_dtype)
            tensor = _astype(tensor, wire)
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and _is_floating(tensor):
            tensor = _astype(tensor, ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast to float16 on the wire (reference: compression.py:46-64)."""
    _wire_dtype = "float16"


class BF16Compressor(_CastCompressor):
    """Cast to bfloat16 on the wire — TPU-native extension; bf16 is the
    MXU/ICI-preferred reduced-precision type."""
    _wire_dtype = "bfloat16"


class Compression:
    """Option enum-alike (reference: compression.py:67-73)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
