"""Autotuner: steer fusion threshold × cycle time for throughput.

(reference: horovod/common/parameter_manager.{h,cc} — joint Bayesian
optimization of fusion-threshold-MB ∈ [0,64] × cycle-time-ms ∈ [1,100],
parameter_manager.h:169-207; score = bytes/µs over samples of
``steps_per_sample`` cycles with median-of-k smoothing,
parameter_manager.cc:28-31,145-171; warmup discard; rank-0 tunes and
the tuned values ride to workers — in the reference via a custom MPI
struct broadcast (cc:64-78), here inside the ResponseList trailer,
which every rank already receives every cycle.)

Enabled with ``HOROVOD_AUTOTUNE=1``; progress optionally logged to
``HOROVOD_AUTOTUNE_LOG`` as CSV.
"""

from __future__ import annotations

import time

import numpy as np

from horovod_tpu.common import logging as hlog
from horovod_tpu.optim.bayesian_optimization import BayesianOptimization

_MB = 1024 * 1024


class ParameterManager:
    def __init__(self, config, controller):
        self._is_coordinator = controller.rank == 0
        self._warmup_remaining = config.autotune_warmup_samples
        self._steps_per_sample = config.autotune_steps_per_sample
        self._max_samples = config.autotune_bayes_opt_max_samples
        self._bo = BayesianOptimization(
            bounds=[(0.0, 64.0), (1.0, 100.0)],  # MB, ms
            alpha=config.autotune_gaussian_process_noise)
        self._log_path = config.autotune_log
        if self._log_path and self._is_coordinator:
            with open(self._log_path, "w") as f:
                f.write("sample,fusion_threshold_mb,cycle_time_ms,"
                        "score_bytes_per_us\n")

        self._current = np.asarray(
            [config.fusion_threshold_bytes / _MB, config.cycle_time_ms])
        self._tuning = self._is_coordinator
        self._samples_taken = 0
        # per-sample accumulation
        self._cycle_count = 0
        self._bytes_acc = 0
        self._t0 = time.monotonic()
        # median-of-k smoothing (reference: median of scores, cc:145-171)
        self._scores = []

    # -- values consumed by the runtime ---------------------------------
    @property
    def tuning(self) -> bool:
        """True while the coordinator's optimizer is still exploring;
        False once converged (or on workers, which never tune). The
        public convergence probe for benchmarks/tests."""
        return self._tuning

    def fusion_threshold_bytes(self) -> int:
        return int(self._current[0] * _MB)

    def cycle_time_ms(self) -> float:
        return float(self._current[1])

    def apply_synced(self, fusion_threshold_bytes: int,
                     cycle_time_ms: float) -> None:
        """Workers adopt the coordinator's tuned values (reference:
        SyncParams, parameter_manager.cc:64-78). The untuned-trailer
        sentinel is cycle_time_ms == 0: real tuned cycle times are
        bounded >= 1 ms, while a FUSION threshold of 0 MB is a
        legitimate tuned value (fusion off) and must still be adopted."""
        if not self._is_coordinator and cycle_time_ms > 0:
            self._current = np.asarray(
                [fusion_threshold_bytes / _MB, cycle_time_ms])

    # -- sampling --------------------------------------------------------
    def on_cycle(self, nbytes: int) -> None:
        """Called by the background loop once per cycle with the bytes
        processed (reference: parameter_manager.cc Update)."""
        if not self._tuning:
            return
        self._bytes_acc += nbytes
        self._cycle_count += 1
        if self._cycle_count < self._steps_per_sample:
            return
        elapsed_us = (time.monotonic() - self._t0) * 1e6
        score = self._bytes_acc / max(elapsed_us, 1.0)
        self._cycle_count = 0
        self._bytes_acc = 0
        self._t0 = time.monotonic()

        if self._warmup_remaining > 0:
            self._warmup_remaining -= 1
            return

        self._scores.append(score)
        if len(self._scores) < 3:
            return
        sample_score = float(np.median(self._scores))
        self._scores = []
        self._samples_taken += 1
        self._bo.add_sample(self._current.copy(), sample_score)
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{self._samples_taken},{self._current[0]:.3f},"
                        f"{self._current[1]:.3f},{sample_score:.6f}\n")
        if self._samples_taken >= self._max_samples:
            best, best_score = self._bo.best()
            if best is not None:
                self._current = np.asarray(best)
            self._tuning = False
            hlog.info(
                f"autotune converged: fusion_threshold="
                f"{self._current[0]:.1f} MB cycle_time="
                f"{self._current[1]:.1f} ms (score {best_score:.3f} B/µs)")
            return
        self._current = np.clip(self._bo.next_sample(),
                                [0.0, 1.0], [64.0, 100.0])
