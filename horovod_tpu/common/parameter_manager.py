"""Autotuner: steer fusion threshold × cycle time for throughput.

(reference: horovod/common/parameter_manager.{h,cc} — joint Bayesian
optimization of fusion-threshold-MB ∈ [0,64] × cycle-time-ms ∈ [1,100],
parameter_manager.h:169-207; score = bytes/µs over samples of
``steps_per_sample`` cycles with median-of-k smoothing,
parameter_manager.cc:28-31,145-171; warmup discard; rank-0 tunes and
the tuned values ride to workers — in the reference via a custom MPI
struct broadcast (cc:64-78), here inside the ResponseList trailer,
which every rank already receives every cycle.)

Enabled with ``HOROVOD_AUTOTUNE=1``; progress optionally logged to
``HOROVOD_AUTOTUNE_LOG`` as CSV.
"""

from __future__ import annotations

import time

import numpy as np

from horovod_tpu.common import logging as hlog
from horovod_tpu.common import wire_dtype as _wd
from horovod_tpu.optim.bayesian_optimization import BayesianOptimization

_MB = 1024 * 1024

# Size buckets for the per-bucket (algorithm, wire dtype) table, by
# UNCOMPRESSED fused-batch bytes: latency-bound small ops, the
# mid-range, and bandwidth-bound large ops. Same shape as the ring
# threshold's reasoning — different sizes want different planes.
BUCKET_BOUNDS = (64 * 1024, 1 << 20)


def bucket_of(nbytes: int) -> int:
    for i, bound in enumerate(BUCKET_BOUNDS):
        if nbytes < bound:
            return i
    return len(BUCKET_BOUNDS)


class _BucketTuner:
    """Measured grid sweep over (ALG_*, WIRE_* cap) combos, one size
    bucket at a time — the discrete half of the autotuner. The
    continuous (fusion threshold, cycle time) pair stays Bayesian;
    these grids are tiny (<= 8 combos) and categorical, so measuring
    every point and keeping the argmax IS the optimal policy — the
    90%-of-best acceptance bar holds by construction, modulo noise
    the median-of-3 smoothing absorbs.

    A bucket that sees no traffic for two consecutive sample windows
    is skipped (keeps the default plan) so an idle bucket can never
    stall convergence. Each combo is measured in TWO interleaved
    passes and scored by the MAX of its samples: scheduler throttle
    bursts (multi-second on shared CI hosts) only ever DEFLATE a
    throughput sample, so the per-combo upper envelope is the robust
    comparator — one pass with adjacent combos landing in different
    throttle phases mis-ranks them."""

    _IDLE_LIMIT = 2
    _PASSES = 2

    def __init__(self, combos, nbuckets: int):
        self._combos = list(combos)
        self._nbuckets = nbuckets
        self._bucket = 0
        self._ci = 0
        self._pass = 0
        self._scores = {}  # (bucket, combo_idx) -> max sample score
        self._idle = 0
        self.done = nbuckets == 0 or len(self._combos) < 2
        self.plan = [(_wd.ALG_DEFAULT, None)] * nbuckets
        # Bumped on every active-combo move (advance, bucket change,
        # settle): the coordinator watches it and force-evicts cached
        # verdicts stamped under the previous plan.
        self.revision = 0

    @property
    def bucket(self) -> int:
        return self._bucket

    def current_combo(self):
        return self._combos[self._ci]

    def feed(self, score: float, bucket_traffic: int,
             total_traffic: int = -1) -> None:
        """One median-of-3 sample measured under the current combo;
        ``bucket_traffic`` is the bytes the bucket under test moved
        during the window (zero = the measurement says nothing about
        this combo). ``total_traffic`` across ALL buckets separates
        "this bucket is idle while the job runs" (a strike toward
        skipping it) from a GLOBAL lull (eval phase, dataloader
        stall — retry without penalty, or a two-window pause would
        permanently forfeit a hot bucket's tuning)."""
        if self.done:
            return
        if bucket_traffic <= 0:
            if total_traffic == 0:
                return  # global pause: says nothing about the bucket
            self._idle += 1
            if self._idle >= self._IDLE_LIMIT:
                self._next_bucket(keep_default=True)
            return
        self._idle = 0
        key = (self._bucket, self._ci)
        self._scores[key] = max(score, self._scores.get(
            key, float("-inf")))
        self._ci += 1
        self.revision += 1
        if self._ci >= len(self._combos):
            self._ci = 0
            self._pass += 1
            if self._pass >= self._PASSES:
                self._next_bucket(keep_default=False)

    def _next_bucket(self, keep_default: bool) -> None:
        self.revision += 1
        if not keep_default:
            best = max(range(len(self._combos)),
                       key=lambda i: self._scores.get(
                           (self._bucket, i), float("-inf")))
            self.plan[self._bucket] = self._combos[best]
        self._bucket += 1
        self._ci = 0
        self._pass = 0
        self._idle = 0
        if self._bucket >= self._nbuckets:
            self.done = True

    def describe(self) -> str:
        return " ".join(
            f"b{i}={_wd.ALG_NAMES[a]}/"
            + ("-" if w is None else _wd.WIRE_NAMES[w])
            for i, (a, w) in enumerate(self.plan))


class _OverlapTuner:
    """Measured sweep over overlap bucket counts — the second discrete
    grid phase, run AFTER the wire sweep and BEFORE the Bayesian phase
    (speculation must stay live while it measures: the overlap tier IS
    a property of the fused speculative regime). Same protocol as
    _BucketTuner: each candidate scored by the MAX of its samples over
    two interleaved passes (throttle bursts only deflate throughput),
    argmax wins. The active candidate rides the ResponseList trailer so
    every rank splits its submissions identically; transient mismatch
    during adoption merely degrades those cycles to the classic path."""

    _PASSES = 2

    def __init__(self, candidates):
        self._candidates = list(candidates)
        self._ci = 0
        self._pass = 0
        self._scores = [float("-inf")] * len(self._candidates)
        self.done = len(self._candidates) < 2
        self.choice = self._candidates[0] if self._candidates else 0

    def current(self) -> int:
        return self._candidates[self._ci]

    def feed(self, score: float, traffic: int) -> None:
        if self.done or traffic <= 0:
            return  # a lull says nothing about the candidate
        self._scores[self._ci] = max(score, self._scores[self._ci])
        self._ci += 1
        if self._ci >= len(self._candidates):
            self._ci = 0
            self._pass += 1
            if self._pass >= self._PASSES:
                best = max(range(len(self._candidates)),
                           key=lambda i: self._scores[i])
                self.choice = self._candidates[best]
                self.done = True


class ParameterManager:
    def __init__(self, config, controller):
        self._is_coordinator = controller.rank == 0
        self._warmup_remaining = config.autotune_warmup_samples
        self._steps_per_sample = config.autotune_steps_per_sample
        self._max_samples = config.autotune_bayes_opt_max_samples
        self._bo = BayesianOptimization(
            bounds=[(0.0, 64.0), (1.0, 100.0)],  # MB, ms
            alpha=config.autotune_gaussian_process_noise)
        self._log_path = config.autotune_log
        if self._log_path and self._is_coordinator:
            with open(self._log_path, "w") as f:
                f.write("sample,fusion_threshold_mb,cycle_time_ms,"
                        "score_bytes_per_us\n")

        self._current = np.asarray(
            [config.fusion_threshold_bytes / _MB, config.cycle_time_ms])
        self._tuning = self._is_coordinator
        self._samples_taken = 0
        # Per-bucket (algorithm, wire-dtype cap) table the coordinator
        # stamps fused responses with (Runtime._stamp_wire_plan). The
        # discrete grid phase (armed via configure_wire) runs before
        # the continuous BO phase; until then — and on workers, who
        # never stamp — the table is all-default.
        nb = len(BUCKET_BOUNDS) + 1
        self._bucket_plan = [(_wd.ALG_DEFAULT, None)] * nb
        self._bucket_tuner = None
        # Overlap bucket-count grid (configure_overlap): None until
        # armed; workers adopt the coordinator's active/settled value
        # from the ResponseList trailer (apply_synced).
        self._overlap_tuner = None
        self._overlap_current = None
        self._bucket_bytes = [0] * nb
        self._bucket_mark = [0] * nb
        # per-sample accumulation
        self._cycle_count = 0
        self._bytes_acc = 0
        self._t0 = time.monotonic()
        # median-of-k smoothing (reference: median of scores, cc:145-171)
        self._scores = []

    # -- wire plan (algorithm x dtype per size bucket) -------------------
    def configure_wire(self, proposed_wire: int, multi_host: bool,
                       world_size: int, shm_enabled: bool = True,
                       ring_allowed: bool = True,
                       ici_allowed: bool = False) -> None:
        """Arm the discrete grid phase (coordinator only). Algorithm
        candidates follow topology AND configuration feasibility
        (ring needs >= 3 ranks and must not be explicitly disabled;
        two-level needs a multi-host world with the shm plane on;
        ICI needs the world-agreed mesh plane — HOROVOD_TPU_ICI with
        every rank holding >= 2 local devices — because a stamped
        combo whose plane cannot engage would just measure default
        routing twice under a misleading name); wire candidates are
        every dtype AT OR BELOW this world's proposal — the tuner
        explores by CAPPING the negotiated verdict, so it can never
        compress harder than the operator asked (numerics-safe)."""
        if not self._is_coordinator or not self._tuning:
            return
        algs = [_wd.ALG_DEFAULT]
        if world_size >= 3 and ring_allowed:
            algs.append(_wd.ALG_RING)
        if multi_host and shm_enabled:
            algs.append(_wd.ALG_TWOLEVEL)
        if ici_allowed:
            algs.append(_wd.ALG_ICI)
        wires = [w for w in (_wd.WIRE_NONE, _wd.WIRE_BF16,
                             _wd.WIRE_FP16, _wd.WIRE_INT8)
                 if w <= proposed_wire]
        combos = [(a, w) for a in algs for w in wires]
        if len(combos) > 1:
            self._bucket_tuner = _BucketTuner(
                combos, len(BUCKET_BOUNDS) + 1)

    def configure_overlap(self, armed: bool) -> None:
        """Add the overlap bucket count to the discrete grid
        (coordinator only, and only when the overlap tier can engage):
        candidates 0 (off), 2, 4, 8 buckets, measured after the wire
        sweep settles and scored by the same bytes/µs stream."""
        if not armed or not self._is_coordinator or not self._tuning:
            return
        self._overlap_tuner = _OverlapTuner([0, 2, 4, 8])

    def overlap_buckets(self):
        """The bucket count the overlap dispatcher should use right
        now, or None when the tuner never armed (static knobs rule).
        Coordinator: the candidate under measurement, then the settled
        argmax. Workers: the value adopted from the trailer."""
        t = self._overlap_tuner
        if t is not None:
            if t.done:
                return t.choice
            # Only measure once the wire sweep settled: both grids
            # share the score stream, and interleaving them would
            # attribute one dimension's effect to the other.
            wt = self._bucket_tuner
            if wt is None or wt.done:
                return t.current()
            return None
        return self._overlap_current

    @property
    def tuned_overlap_buckets(self) -> int:
        """Trailer value the coordinator stamps each cycle: the active
        candidate/settled choice, or -1 (no verdict) while unarmed."""
        v = self.overlap_buckets() if self._is_coordinator else None
        return -1 if v is None else int(v)

    def plan(self, nbytes: int):
        """-> (ALG_* code, wire cap or None) for one fused batch —
        the coordinator's stamping policy (Runtime._stamp_wire_plan).
        While the grid phase runs, the bucket under test answers with
        the combo being measured; everything else follows the
        settled table."""
        b = bucket_of(nbytes)
        self._bucket_bytes[b] += nbytes
        t = self._bucket_tuner
        if t is not None and not t.done:
            if b == t.bucket:
                return t.current_combo()
            if b < t.bucket:
                # Already-settled buckets stamp their measured argmax
                # IMMEDIATELY: later buckets must be scored in the
                # regime the final plan will deploy, and the settled
                # combo's speedup starts paying during the rest of
                # the sweep instead of after it.
                return t.plan[b]
        return self._bucket_plan[b]

    def bucket_plan(self):
        """The settled per-bucket (algorithm, wire cap) table —
        benchmark/test surface."""
        return list(self._bucket_plan)

    @property
    def plan_revision(self) -> int:
        """Monotone counter of active-plan moves (combo advances +
        the final convergence), watched by the coordinator to
        force-evict cached verdicts stamped under a superseded plan —
        the mechanism that lets autotune and the response cache
        coexist."""
        rev = self._bucket_tuner.revision \
            if self._bucket_tuner is not None else 0
        # +1 at convergence: the last eviction resets spec-denial
        # slates (epoch move), so the fused speculative cycle
        # re-engages for the tuned steady state.
        return rev + (0 if self._tuning else 1)

    @property
    def spec_safe(self) -> bool:
        """May the fused speculative cycle run? Yes on workers (their
        bids are opportunistic by design), yes through the discrete
        grid phase (combo scores must measure the DEPLOYMENT regime,
        spec cycle included — its parameters are frozen), yes after
        convergence; no only while the Bayesian phase steers
        fusion/cycle values through full-response trailers that
        speculative cycles would starve."""
        if not self._is_coordinator or not self._tuning:
            return True
        t = self._bucket_tuner
        if t is not None and not t.done:
            return True
        # The overlap grid ALSO needs live speculation: its candidates
        # are properties of the fused speculative regime.
        ot = self._overlap_tuner
        return ot is not None and not ot.done

    # -- values consumed by the runtime ---------------------------------
    @property
    def tuning(self) -> bool:
        """True while the coordinator's optimizer is still exploring;
        False once converged (or on workers, which never tune). The
        public convergence probe for benchmarks/tests."""
        return self._tuning

    def fusion_threshold_bytes(self) -> int:
        return int(self._current[0] * _MB)

    def cycle_time_ms(self) -> float:
        return float(self._current[1])

    def apply_synced(self, fusion_threshold_bytes: int,
                     cycle_time_ms: float,
                     overlap_buckets: int = -1) -> None:
        """Workers adopt the coordinator's tuned values (reference:
        SyncParams, parameter_manager.cc:64-78). The untuned-trailer
        sentinel is cycle_time_ms == 0: real tuned cycle times are
        bounded >= 1 ms, while a FUSION threshold of 0 MB is a
        legitimate tuned value (fusion off) and must still be adopted.
        ``overlap_buckets`` uses -1 as its sentinel (0 = tuned OFF is
        a legitimate verdict)."""
        if not self._is_coordinator and cycle_time_ms > 0:
            self._current = np.asarray(
                [fusion_threshold_bytes / _MB, cycle_time_ms])
        if not self._is_coordinator and overlap_buckets >= 0:
            self._overlap_current = overlap_buckets

    # -- sampling --------------------------------------------------------
    def on_cycle(self, nbytes: int) -> None:
        """Called by the background loop once per cycle with the bytes
        processed (reference: parameter_manager.cc Update)."""
        if not self._tuning:
            return
        self._bytes_acc += nbytes
        self._cycle_count += 1
        if self._cycle_count < self._steps_per_sample:
            return
        elapsed_us = (time.monotonic() - self._t0) * 1e6
        score = self._bytes_acc / max(elapsed_us, 1.0)
        self._cycle_count = 0
        self._bytes_acc = 0
        self._t0 = time.monotonic()

        if self._warmup_remaining > 0:
            self._warmup_remaining -= 1
            return

        self._scores.append(score)
        if len(self._scores) < 3:
            return
        sample_score = float(np.median(self._scores))
        self._scores = []

        # Phase 1 — discrete grid: route median samples to the bucket
        # tuner until every (algorithm, wire) combo of every
        # traffic-bearing bucket has been measured; the continuous BO
        # phase below then runs against the SETTLED table.
        t = self._bucket_tuner
        if t is not None and not t.done:
            b = t.bucket
            traffic = self._bucket_bytes[b] - self._bucket_mark[b]
            total = sum(self._bucket_bytes) - sum(self._bucket_mark)
            self._bucket_mark = list(self._bucket_bytes)
            t.feed(sample_score, traffic, total)
            if t.done:
                self._bucket_plan = list(t.plan)
                hlog.info("autotune wire plan settled: "
                          + t.describe())
            return

        # Phase 2 — overlap bucket-count grid (speculation stays live;
        # see spec_safe). Scored by total traffic: bucketing reshapes
        # every allreduce, not one size bucket.
        ot = self._overlap_tuner
        if ot is not None and not ot.done:
            total = sum(self._bucket_bytes) - sum(self._bucket_mark)
            self._bucket_mark = list(self._bucket_bytes)
            ot.feed(sample_score, total)
            if ot.done:
                hlog.info(f"autotune overlap bucket count settled: "
                          f"{ot.choice}")
            return

        self._samples_taken += 1
        self._bo.add_sample(self._current.copy(), sample_score)
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{self._samples_taken},{self._current[0]:.3f},"
                        f"{self._current[1]:.3f},{sample_score:.6f}\n")
        if self._samples_taken >= self._max_samples:
            best, best_score = self._bo.best()
            if best is not None:
                self._current = np.asarray(best)
            self._tuning = False
            hlog.info(
                f"autotune converged: fusion_threshold="
                f"{self._current[0]:.1f} MB cycle_time="
                f"{self._current[1]:.1f} ms (score {best_score:.3f} B/µs)")
            return
        self._current = np.clip(self._bo.next_sample(),
                                [0.0, 1.0], [64.0, 100.0])
