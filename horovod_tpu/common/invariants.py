"""Machine-checked invariant annotations (see docs/static_analysis.md).

``@world_coherent`` marks a function whose inputs are world-identical
by construction — the broadcast response stream, the coordinator's
grant/invalidate masks, the fused speculative verdict. hvdlint's
``world-coherence`` analyzer enforces that every mutation of
world-replicated state (the attributes carrying a
``# hvdlint: world-replicated`` marker: the ResponseCache's
slots/LRU/epoch, the runtime's steady predictor) is reachable ONLY
through functions carrying this decorator. The decorator itself is
identity at runtime; its value is that removing it — or adding a new
rank-local call path to coherent state — fails the lint tier instead
of diverging a live world.
"""

from __future__ import annotations


def world_coherent(fn):
    """Identity decorator: this function applies only world-identical
    inputs, in the canonical world order, and may therefore mutate
    world-replicated state (enforced by `python -m tools.hvdlint`)."""
    fn.__world_coherent__ = True
    return fn
