"""Wire-dtype gradient compression: the on-the-wire codec.

Upstream Horovod's ``Compression`` API casts gradients to fp16 at the
FRAMEWORK layer (Sergeev & Del Balso 2018) — every byte the data plane
moves is already half-width by the time the runtime sees it. This
module is the TPU-native deepening of that idea: the wire dtype is a
**per-request negotiated attribute**, resolved by the coordinator to
one world-coherent choice per fused batch (the common denominator of
every rank's proposal) and broadcast in the Response, and the op
backends compress into the fusion arenas / decompress on recv-into so
only WIRE bytes shrink — user tensors, accumulators and outputs keep
their full dtype. ``int8`` adds per-tensor error-feedback residuals
(Deep Gradient Compression, Lin et al. 2018): the quantization error
of step k is added back into step k+1's payload, so the time-averaged
update is unbiased.

This module is also THE shared dtype table (satellite of ISSUE 9): the
framework-level ``common/compression.py`` helper and this wire codec
both answer "is this tensor a float?" through :func:`is_floating_name`
— the previous string matching in two places is exactly how jax/
ml_dtypes ``bfloat16`` fell through one of them.

Code families (one byte each on the wire; hvdlint's wire-protocol
analyzer enforces pairwise distinctness per family):

* ``WIRE_*`` — the negotiated wire dtype of a payload.  Ordered by
  aggressiveness: the coordinator resolves a fused batch to the MIN
  over ranks, so one rank proposing ``none`` degrades the whole batch
  to uncompressed (heterogeneous knobs negotiate, never crash).
* ``ALG_*`` — the collective algorithm the coordinator stamps on a
  fused Response: ``DEFAULT`` keeps each backend's own routing
  heuristics (byte-identical to pre-compression behavior), ``STAR``/
  ``RING`` force the flat socket paths, ``TWOLEVEL`` selects the
  hierarchical intra-host-reduce / cross-host-ring / intra-host-
  broadcast plane (ops/shm_ops.py), ``ICI`` runs the pre-compiled
  fused-psum XLA executable over the local device mesh for the
  intra-slice leg (ops/xla_ops.py IciPlane) and the compressed
  socket/ring plane for the cross-slice (DCN) leg.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.common.message import DataType

# -- wire dtype codes (u8 on the wire; min-resolved across ranks) ------
WIRE_NONE = 0
WIRE_BF16 = 1
WIRE_FP16 = 2
WIRE_INT8 = 3

# -- algorithm codes (u8 on the wire; stamped by the coordinator) ------
ALG_DEFAULT = 0
ALG_STAR = 1
ALG_RING = 2
ALG_TWOLEVEL = 3
ALG_ICI = 4

WIRE_NAMES = {WIRE_NONE: "none", WIRE_BF16: "bf16",
              WIRE_FP16: "fp16", WIRE_INT8: "int8"}
_NAME_WIRES = {v: k for k, v in WIRE_NAMES.items()}
ALG_NAMES = {ALG_DEFAULT: "default", ALG_STAR: "star",
             ALG_RING: "ring", ALG_TWOLEVEL: "twolevel",
             ALG_ICI: "ici"}

# Request dtypes a wire cast can shrink. fp16/bf16 tensors are already
# half-width and int tensors have no meaningful reduced-precision sum.
COMPRESSIBLE = frozenset((DataType.FLOAT32, DataType.FLOAT64))

# THE shared float-dtype table (see module docstring): numpy builtin
# names plus the ml_dtypes extension names jax surfaces on host
# buffers. Both common/compression.py and this codec consult it.
FLOATING_DTYPE_NAMES = frozenset((
    "float16", "float32", "float64", "bfloat16",
    "float8_e4m3fn", "float8_e5m2",
))

# int8 wire layout: one f32 scale, then count int8 lanes. The scale
# rides inside the payload (not the control frame) so every data plane
# that can move bytes can move quantized tensors unchanged.
_INT8_HDR = 4


def is_floating_name(name: str) -> bool:
    return name in FLOATING_DTYPE_NAMES


def is_floating(dtype_like) -> bool:
    """Shared float probe for numpy/jax/ml_dtypes dtypes — name-keyed
    via one table instead of per-call string lists. jax array dtypes
    (including ``jax.numpy.bfloat16``) expose ``.name``; anything else
    normalizes through ``np.dtype``."""
    name = getattr(dtype_like, "name", None)
    if name is None:
        name = np.dtype(dtype_like).name
    return name in FLOATING_DTYPE_NAMES


def wire_code_of(name: str) -> int:
    """Knob string -> WIRE_* code; raises on a typo (a silently-picked
    default would diverge ranks' proposals without anyone noticing)."""
    code = _NAME_WIRES.get(name.strip().lower())
    if code is None:
        raise ValueError(
            f"HOROVOD_COMPRESSION={name!r}: must be one of "
            f"{sorted(_NAME_WIRES)}")
    return code


def ring_wire(wire: int) -> int:
    """The wire dtype a RING leg actually carries: per-rank int8
    scales cannot sum link-by-link, so int8 degrades to bf16 — ONE
    rule shared by every plane that routes onto a ring (the route and
    the verdict are both world-identical, so the degrade is too)."""
    return WIRE_BF16 if wire == WIRE_INT8 else wire


def allgather_wire(wire: int) -> int:
    """The wire dtype an ALLGATHER verdict can carry: the gathered
    world blob is ONE payload whose blocks concatenate byte-for-byte,
    and per-rank int8 scale headers cannot ride inside a single
    contiguous buffer, so int8 degrades to bf16 (the cast wires
    concatenate losslessly). Stamped by the coordinator, so the
    degrade is world-identical like :func:`ring_wire`'s."""
    return WIRE_BF16 if wire == WIRE_INT8 else wire


def resolve(codes) -> int:
    """The world's common denominator for one tensor's proposals: the
    LEAST aggressive request wins, so a single rank launched with
    compression off degrades the batch to a dtype every rank can
    speak. (Knob heterogeneity only — every rank must run the same
    wire layout, since the proposal byte rides the control frames.)"""
    out = None
    for c in codes:
        out = c if out is None else min(out, c)
    return WIRE_NONE if out is None else out


def _np_bf16():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)


def wire_np_dtype(wire: int):
    """numpy dtype of the wire lanes for the CAST wires; int8 payloads
    are raw uint8 (scale header + lanes) and have no single lane
    dtype."""
    if wire == WIRE_BF16:
        return _np_bf16()
    if wire == WIRE_FP16:
        return np.dtype(np.float16)
    raise ValueError(f"wire dtype {wire} has no lane dtype")


def wire_datatype(wire: int) -> DataType:
    """DataType a compressed spec-frame segment declares on the wire
    (cast wires only — int8 never rides the speculative fused cycle)."""
    if wire == WIRE_BF16:
        return DataType.BFLOAT16
    if wire == WIRE_FP16:
        return DataType.FLOAT16
    raise ValueError(f"wire dtype {wire} has no DataType")


def compressed_nbytes(wire: int, count: int, src_itemsize: int) -> int:
    """Payload bytes ``count`` elements occupy at ``wire``."""
    if wire == WIRE_NONE:
        return count * src_itemsize
    if wire in (WIRE_BF16, WIRE_FP16):
        return count * 2
    if wire == WIRE_INT8:
        return _INT8_HDR + count
    raise ValueError(f"unknown wire dtype {wire}")


def compress(arr: np.ndarray, wire: int,
             out: Optional[np.ndarray] = None) -> np.ndarray:
    """Flat contiguous src array -> wire representation. ``out`` (a
    preallocated wire-dtype view, e.g. a fusion-arena region) makes
    the cast allocation-free on the steady path; int8 callers go
    through :func:`quantize` instead (the scale must be computed)."""
    if wire == WIRE_NONE:
        return arr
    if wire == WIRE_INT8:
        return quantize(arr)
    np_wire = wire_np_dtype(wire)
    if out is None:
        return arr.astype(np_wire)
    cast_into(arr, out)
    return out


def cast_into(src: np.ndarray, dst: np.ndarray) -> None:
    """dst[:] = src with a dtype cast — the native ``hvd_cast`` kernel
    when it speaks both dtypes (f32<->bf16/f16), numpy's casting
    machinery otherwise. Never allocates a payload-sized temporary on
    the native path."""
    from horovod_tpu import native as _native
    if not _native.cast_into(src, dst):
        # ml_dtypes registers numpy casts, so copyto handles the
        # bf16 directions too; 'unsafe' covers f64 sources.
        np.copyto(dst, src, casting="unsafe")


def decompress(buf, wire: int, src_np_dtype, count: int) -> np.ndarray:
    """Wire bytes/array -> a FRESH array of the tensor's real dtype
    (fresh on purpose: decompressed results back user-visible outputs,
    which must never alias wire/arena memory)."""
    src_np_dtype = np.dtype(src_np_dtype)
    if wire == WIRE_NONE:
        a = buf if isinstance(buf, np.ndarray) \
            else np.frombuffer(buf, dtype=src_np_dtype)
        return np.array(a, dtype=src_np_dtype, copy=True)
    if wire == WIRE_INT8:
        return dequantize(buf, src_np_dtype, count)
    np_wire = wire_np_dtype(wire)
    w = buf if isinstance(buf, np.ndarray) and buf.dtype == np_wire \
        else np.frombuffer(buf, dtype=np_wire, count=count)
    out = np.empty(count, src_np_dtype)
    cast_into(w, out)
    return out


# -- int8 with error feedback ------------------------------------------

# Fallback-copy observability hook (hvd_data_copies_total — the SAME
# counter as socket_ops/runtime by registry name-memoization, attached
# by SocketBackend.attach_metrics). The numpy codec legs materialize
# payload-sized temporaries the native codec (hvd_quant8/hvd_dequant8)
# deletes; ticking them per leg keeps "is the zero-copy plane
# engaged" an honest single metrics read. None (unattached) records
# nothing.
_COPY_METRIC = None


def attach_copy_counter(metric) -> None:
    global _COPY_METRIC
    _COPY_METRIC = metric


def _count_copy() -> None:
    m = _COPY_METRIC
    if m is not None:
        m.inc()


def _quantize_numpy(arr: np.ndarray, buf: np.ndarray) -> None:
    """The numpy codec leg (bit-identical reference of hvd_quant8's
    plain mode): payload-sized temporaries and all — counted as ONE
    fallback copy."""
    n = arr.size
    scale = float(np.max(np.abs(arr))) / 127.0 if n else 0.0
    if scale == 0.0:
        scale = 1.0
    buf[:_INT8_HDR].view(np.float32)[0] = scale
    q = buf[_INT8_HDR:].view(np.int8)
    # two-step on purpose: rint in float, clip, then narrow — a direct
    # int8 cast of an out-of-range float is undefined in numpy
    tmp = np.rint(arr * (arr.dtype.type(1.0 / scale)))
    np.clip(tmp, -127, 127, out=tmp)
    q[:] = tmp.astype(np.int8)
    _count_copy()


def quantize(arr: np.ndarray) -> np.ndarray:
    """f32/f64 -> [f32 scale | int8 lanes] as one uint8 buffer. Scale
    is max|x|/127 (1.0 for an all-zero tensor so dequantize is exact);
    lanes round to nearest-even. One native pass (hvd_quant8) when the
    core speaks the dtype — scale scan, scaled round and saturate
    without a single payload temporary, bit-identical to the numpy
    leg."""
    from horovod_tpu import native as _native
    buf = np.empty(_INT8_HDR + arr.size, np.uint8)
    if not _native.quant8(arr, buf):
        _quantize_numpy(np.ascontiguousarray(arr), buf)
    return buf


def quantize_ef(arr: np.ndarray, ef: "ErrorFeedback",
                key: tuple) -> np.ndarray:
    """int8 quantize with FUSED error feedback: compensate
    (arr + residual), scan, quantize and store the next-step residual
    in one native pass (hvd_quant8 with residual buffers) instead of
    the apply -> quantize -> update triple and its three payload
    temporaries. Bit-identical to the classic triple — the fallback
    IS the classic triple."""
    from horovod_tpu import native as _native
    res_in = ef.residual(key, arr)
    res_out = ef.residual_buffer(key, arr)
    buf = np.empty(_INT8_HDR + arr.size, np.uint8)
    if _native.quant8(arr, buf, residual=res_in,
                      residual_out=res_out):
        ef.put(key, res_out)
        return buf
    comp = ef.apply(key, arr)
    _quantize_numpy(comp, buf)
    ef.update(key, comp, buf)
    return buf


def dequantize(buf, src_np_dtype, count: int) -> np.ndarray:
    """[scale|int8] buffer -> fresh src-dtype array. Native single
    pass (hvd_dequant8) when available; the numpy leg round-trips a
    payload-sized astype temporary (counted)."""
    from horovod_tpu import native as _native
    src_np_dtype = np.dtype(src_np_dtype)
    raw = np.frombuffer(buf, np.uint8, count=_INT8_HDR + count)
    out = np.empty(count, src_np_dtype)
    if _native.dequant8(raw, out):
        return out
    scale = float(raw[:_INT8_HDR].view(np.float32)[0])
    q = raw[_INT8_HDR:].view(np.int8)
    np.multiply(q.astype(src_np_dtype),
                np.asarray(scale, src_np_dtype), out=out)
    _count_copy()
    return out


class ErrorFeedback:
    """Per-tensor-batch int8 residual store (rank-LOCAL by design —
    each rank compensates its OWN quantization error, so residuals
    legitimately differ across ranks and are deliberately NOT
    world-replicated state). Keyed by the fused batch's name tuple:
    steady training loops repeat the same batches, and a membership
    change simply starts a fresh residual. LRU-capped: past _CAP
    keys the OLDEST residual is dropped — never the whole store, or
    a workload with more distinct batches than the cap would lose
    every compensation chain on every step."""

    _CAP = 64

    def __init__(self):
        from collections import OrderedDict
        self._residuals: "OrderedDict[tuple, np.ndarray]" = \
            OrderedDict()

    def apply(self, key: tuple, arr: np.ndarray) -> np.ndarray:
        """arr + residual(key) as a FRESH array (never mutates arr —
        it may alias a caller tensor or arena memory)."""
        r = self._residuals.get(key)
        if r is None or r.size != arr.size:
            return np.array(arr, copy=True)
        return arr + r.astype(arr.dtype, copy=False)

    def update(self, key: tuple, compensated: np.ndarray,
               qbuf: np.ndarray) -> None:
        """residual = compensated - dequant(sent): what the wire lost
        this step rides into the next one."""
        if key not in self._residuals \
                and len(self._residuals) >= self._CAP:
            self._residuals.popitem(last=False)
        sent = dequantize(qbuf, compensated.dtype, compensated.size)
        self._residuals[key] = compensated - sent
        self._residuals.move_to_end(key)

    # -- fused native entry (quantize_ef / hvd_quant8) -----------------
    def residual(self, key: tuple, arr: np.ndarray):
        """The stored residual when it can feed the native fused pass
        directly (same lane count AND dtype — a mismatch starts a
        fresh compensation chain, exactly like apply's size check)."""
        r = self._residuals.get(key)
        if r is None or r.size != arr.size or r.dtype != arr.dtype:
            return None
        return r

    def residual_buffer(self, key: tuple, arr: np.ndarray) -> np.ndarray:
        """Destination for the fused pass's next-step residual. The
        stored residual itself when it matches — hvd_quant8 reads lane
        i before overwriting it, so aliasing residual/residual_out is
        safe and saves the allocation — else a fresh buffer."""
        r = self._residuals.get(key)
        if r is not None and r.size == arr.size \
                and r.dtype == arr.dtype:
            return r
        return np.empty(arr.size, arr.dtype)

    def put(self, key: tuple, residual: np.ndarray) -> None:
        """Store a residual computed by the fused native pass (the
        update() twin without the dequantize round-trip)."""
        if key not in self._residuals \
                and len(self._residuals) >= self._CAP:
            self._residuals.popitem(last=False)
        self._residuals[key] = residual
        self._residuals.move_to_end(key)

    def drop(self, key: tuple) -> None:
        self._residuals.pop(key, None)


def reduce_wire(own: np.ndarray, peers: List, wire: int,
                src_np_dtype, count: int) -> np.ndarray:
    """Coordinator-side reduction of compressed contributions, rank
    order (own first). Cast wires sum IN the wire dtype — exactly what
    the native steady coordinator does via ``hvd_sum_into``, so the
    Python and C legs are numerically interchangeable. int8 payloads
    carry per-rank scales, so the coordinator dequantizes each into a
    full-precision accumulator and requantizes the world sum with a
    fresh scale for the broadcast. Returns the wire buffer to
    broadcast (``own`` is consumed as the accumulator for cast
    wires — callers pass a fresh array)."""
    from horovod_tpu import native as _native
    if wire in (WIRE_BF16, WIRE_FP16):
        np_wire = wire_np_dtype(wire)
        acc = own
        for p in peers:
            src = p if isinstance(p, np.ndarray) and p.dtype == np_wire \
                else np.frombuffer(p, dtype=np_wire, count=count)
            if not _native.sum_into(acc, src):
                acc += src
        return acc
    assert wire == WIRE_INT8
    accf = dequantize(own, src_np_dtype, count)
    for p in peers:
        accf += dequantize(p, src_np_dtype, count)
    return quantize(accf)


class StaticWirePolicy:
    """The non-autotuned (algorithm, wire-dtype cap) policy the
    coordinator stamps fused allreduce batches with: two-level for
    multi-host batches at/above the threshold when HOROVOD_TWO_LEVEL
    is set, each backend's own routing otherwise; never caps the
    negotiated wire dtype (the request proposals already carry the
    operator's choice). Two-level additionally requires the shm plane
    (its intra-host legs live there) — a stamp whose plane cannot
    engage would silently no-op as default routing. ICI (the world-
    agreed mesh plane, HOROVOD_TPU_ICI) outranks two-level: the fused
    batch packs/casts/reduces on-device and only the pre-compressed
    wire buffer touches the cross-slice socket plane. The autotuned
    twin is ParameterManager.plan (common/parameter_manager.py)."""

    def __init__(self, two_level: bool, threshold_bytes: int,
                 multi_host: bool, shm_enabled: bool = True,
                 ici_allowed: bool = False,
                 ici_threshold_bytes: int = 0):
        self._two_level = bool(two_level) and multi_host and shm_enabled
        self._threshold = max(0, int(threshold_bytes))
        self._ici = bool(ici_allowed)
        self._ici_threshold = max(0, int(ici_threshold_bytes))

    def plan(self, nbytes: int):
        """-> (ALG_* code, wire cap or None)."""
        if self._ici and nbytes >= self._ici_threshold:
            return ALG_ICI, None
        if self._two_level and nbytes >= self._threshold:
            return ALG_TWOLEVEL, None
        return ALG_DEFAULT, None


# -- process-wide "wire compression is active" latch -------------------
# Set by basics.init from Config.compression; consulted by the
# framework-level Compression helpers so a job that enables wire
# compression does not ALSO cast at the framework layer (double
# compression would quantize twice and decompress once).

_ACTIVE = WIRE_NONE


def set_active(code: int) -> None:
    global _ACTIVE
    _ACTIVE = code


def active() -> int:
    return _ACTIVE
