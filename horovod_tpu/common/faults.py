"""Deterministic fault injection for the fail-fast abort machinery.

Real process death is easy to cause from a test (SIGKILL) but hard to
*time*: proving that survivors detect a peer that dies mid-collective,
or one that goes silent without the kernel sending a FIN/RST, needs
the failure to land at an exact point of the negotiation cycle. This
module injects those failures from inside the background loop itself.

Faults are armed either through the API::

    from horovod_tpu.common import faults
    faults.install(action="kill", at_cycle=200)          # SIGKILL self
    faults.install(action="hang", at_cycle=50, seconds=8)

or through the environment, so launcher-spawned ranks can be faulted
without code changes::

    HOROVOD_FAULT_SPEC="rank=1:kill:cycle=40;rank=2:delay:op=3:ms=50"

Grammar: directives separated by ``;``; each directive is ``:``-joined
tokens — one bare action word plus ``key=value`` arguments. ``rank``
scopes the directive to one global rank — the LAUNCH-TIME identity
(HOROVOD_RANK), which stays stable even when an elastic resize
renumbers the survivors (absent = every rank). Exactly
one trigger is required: ``cycle=K`` fires at the K-th negotiation
cycle, ``op=K`` fires just before the K-th executed response (i.e.
after negotiation, squarely mid-collective), and ``rdzv=K`` fires on
entry to this process's K-th elastic re-rendezvous barrier
(common/elastic.py) — the double-fault case: a member dying DURING
recovery. ``rank`` in an ``rdzv`` directive matches the member's rank
in the world that just aborted.

Actions:

- ``kill``  — SIGKILL this process (no cleanup, no FIN from user space;
  the abrupt-death case).
- ``exit``  — ``os._exit(code)`` (``code=N``, default 1).
- ``hang``  — stop the background loop for ``seconds=S`` (default 60):
  the process stays alive but goes silent, which is the only way to
  exercise the heartbeat deadline rather than TCP reset detection.
- ``sever`` — close this rank's control channel(s) (``target=R``
  selects one peer on the coordinator/local root), simulating link
  loss.
- ``delay`` — sleep ``ms=N`` milliseconds once (latency injection).
  ``count=K`` repeats the delay on K consecutive trigger hits (one
  per cycle/op), turning a one-shot hiccup into a sustained
  straggler — the lever the world-trace tests use to pin last-arriver
  attribution on a specific rank.
- ``preempt`` — SIGTERM this process with ``seconds=S`` of grace
  (default HOROVOD_PREEMPT_GRACE) before a hard SIGKILL, the cloud
  spot/preemptible-VM shape. The supervision layer
  (common/selfop.py) catches the SIGTERM, drains the current step
  and retires the rank cleanly inside the grace window — the
  regression lever for the proactive drain-and-resize path.

The module is zero-cost when idle: the runtime's per-cycle/per-op
ticks return after a single ``_PLAN`` check.
"""

from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import logging as hlog

_ACTIONS = ("kill", "exit", "hang", "sever", "delay", "preempt")


class Fault:
    """One armed fault directive."""

    __slots__ = ("action", "rank", "at_cycle", "at_op", "at_rdzv",
                 "seconds", "ms", "code", "target", "count", "fired")

    def __init__(self, action: str, rank: Optional[int] = None,
                 at_cycle: Optional[int] = None,
                 at_op: Optional[int] = None,
                 at_rdzv: Optional[int] = None, seconds: float = 60.0,
                 ms: float = 0.0, code: int = 1,
                 target: Optional[int] = None, count: int = 1):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; "
                             f"expected one of {_ACTIONS}")
        if count != 1 and action != "delay":
            raise ValueError(
                "count= repeats only make sense for delay faults "
                f"(a fired {action!r} never returns)")
        if count < 1:
            raise ValueError(f"fault count must be >= 1, got {count}")
        triggers = [t for t in (at_cycle, at_op, at_rdzv)
                    if t is not None]
        if len(triggers) != 1:
            raise ValueError(
                "a fault needs exactly one trigger: at_cycle=, at_op= "
                "or at_rdzv=")
        if action == "sever" and at_rdzv is not None:
            raise ValueError(
                "sever has no channel to cut during re-rendezvous; "
                "use kill/exit/hang/delay with rdzv=")
        self.action = action
        self.rank = rank
        self.at_cycle = at_cycle
        self.at_op = at_op
        self.at_rdzv = at_rdzv
        self.seconds = seconds
        self.ms = ms
        self.code = code
        self.target = target
        self.count = count
        self.fired = False

    def __repr__(self) -> str:
        if self.at_cycle is not None:
            trig = f"cycle={self.at_cycle}"
        elif self.at_op is not None:
            trig = f"op={self.at_op}"
        else:
            trig = f"rdzv={self.at_rdzv}"
        scope = "*" if self.rank is None else self.rank
        return f"Fault({self.action}@{trig}, rank={scope})"


_PLAN: Optional[List[Fault]] = None
_ENV_LOADED = False


def parse_spec(spec: str) -> List[Fault]:
    """Parse a HOROVOD_FAULT_SPEC string; raises ValueError on garbage
    (a typo'd fault spec silently doing nothing would invalidate the
    test that relied on it)."""
    faults: List[Fault] = []
    for directive in spec.split(";"):
        directive = directive.strip()
        if not directive:
            continue
        action = None
        kw = {}
        for token in directive.split(":"):
            token = token.strip()
            if not token:
                continue
            if "=" in token:
                k, v = token.split("=", 1)
                k = k.strip()
                if k == "rank":
                    kw["rank"] = int(v)
                elif k == "cycle":
                    kw["at_cycle"] = int(v)
                elif k == "op":
                    kw["at_op"] = int(v)
                elif k == "rdzv":
                    kw["at_rdzv"] = int(v)
                elif k == "seconds":
                    kw["seconds"] = float(v)
                elif k == "ms":
                    kw["ms"] = float(v)
                elif k == "code":
                    kw["code"] = int(v)
                elif k == "target":
                    kw["target"] = int(v)
                elif k == "count":
                    kw["count"] = int(v)
                else:
                    raise ValueError(
                        f"unknown fault key {k!r} in {directive!r}")
            else:
                if action is not None:
                    raise ValueError(
                        f"two actions in one directive: {directive!r}")
                action = token
        if action is None:
            raise ValueError(f"fault directive has no action: "
                             f"{directive!r}")
        faults.append(Fault(action, **kw))
    return faults


def install(action: str, rank: Optional[int] = None,
            at_cycle: Optional[int] = None, at_op: Optional[int] = None,
            **kw) -> Fault:
    """Arm one fault programmatically (test/API path)."""
    global _PLAN
    f = Fault(action, rank=rank, at_cycle=at_cycle, at_op=at_op, **kw)
    if _PLAN is None:
        _PLAN = []
    _PLAN.append(f)
    return f


def clear() -> None:
    global _PLAN, _ENV_LOADED, _RDZV_COUNT
    _PLAN = None
    _ENV_LOADED = False
    _RDZV_COUNT = 0


def load_env() -> None:
    """Arm faults from HOROVOD_FAULT_SPEC, once per process."""
    global _PLAN, _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    spec = hconfig.env_str("HOROVOD_FAULT_SPEC", "")
    if not spec:
        return
    parsed = parse_spec(spec)
    if _PLAN is None:
        _PLAN = []
    _PLAN.extend(parsed)


def _apply(fault: Fault, runtime, rank: Optional[int] = None) -> None:
    """``runtime`` may be None for rendezvous-triggered faults (the
    old runtime is already torn down there); ``rank`` then labels the
    log line."""
    fault.count -= 1
    if fault.count <= 0:
        fault.fired = True
    if rank is None:
        rank = runtime.controller.rank
    hlog.warning(f"fault injection firing on rank {rank}: {fault!r}",
                 rank=rank)
    from horovod_tpu.common import trace as htrace
    htrace.flight().record(htrace.EV_FAULT,
                           note=f"{fault!r} fired on rank {rank}")
    if fault.action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.action == "exit":
        os._exit(fault.code)
    elif fault.action == "hang":
        time.sleep(fault.seconds)
    elif fault.action == "delay":
        time.sleep(fault.ms / 1000.0)
    elif fault.action == "sever" and runtime is not None:
        runtime.controller.sever_connection(fault.target)
    elif fault.action == "preempt":
        # A real preemption notice: SIGTERM now, SIGKILL after the
        # grace window. The timer backstop fires even if nothing
        # handles the SIGTERM — exactly the cloud contract.
        import threading
        from horovod_tpu.common import selfop
        grace = fault.seconds if fault.seconds != 60.0 else \
            hconfig.env_float("HOROVOD_PREEMPT_GRACE", 30.0)
        t = threading.Timer(grace, os.kill,
                            args=(os.getpid(), signal.SIGKILL))
        t.daemon = True
        t.start()
        if selfop.install_signal_handler():
            os.kill(os.getpid(), signal.SIGTERM)
        else:
            # The tick runs on the background loop, not the main
            # thread — the handler may be uninstallable. Arm the
            # drain flag directly; the semantics are identical.
            selfop.notice_preemption()


def _tick(runtime, cycle: Optional[int], op: Optional[int]) -> None:
    # Scope on the LAUNCH-TIME identity (HOROVOD_RANK), not the
    # current controller rank: an elastic resize renumbers survivors
    # densely, and a directive for "rank 0" must keep meaning the
    # process the launcher started as rank 0 — not whoever inherited
    # that rank after a re-election (which would make every newly
    # promoted coordinator re-fire a spent coordinator-kill fault).
    rank = hconfig.env_int("HOROVOD_RANK", runtime.controller.rank)
    for f in _PLAN:  # type: ignore[union-attr]
        if f.fired or (f.rank is not None and f.rank != rank):
            continue
        if cycle is not None and f.at_cycle is not None \
                and cycle >= f.at_cycle:
            _apply(f, runtime)
        elif op is not None and f.at_op is not None and op >= f.at_op:
            _apply(f, runtime)


def tick_cycle(runtime, cycle: int) -> None:
    """Called by the background loop at the top of every cycle."""
    if _PLAN is None:
        return
    _tick(runtime, cycle, None)


def tick_op(runtime, op_index: int) -> None:
    """Called just before executing each negotiated response."""
    if _PLAN is None:
        return
    _tick(runtime, None, op_index)


_RDZV_COUNT = 0


def tick_rendezvous(rank: int) -> None:
    """Called by common/elastic.py on entry to each re-rendezvous
    barrier, so a fault can land squarely DURING recovery (the
    double-fault case). ``rank`` is this member's rank in the world
    that just aborted."""
    global _RDZV_COUNT
    _RDZV_COUNT += 1
    if _PLAN is None:
        return
    rank = hconfig.env_int("HOROVOD_RANK", rank)  # launch identity
    for f in _PLAN:
        if f.fired or f.at_rdzv is None \
                or (f.rank is not None and f.rank != rank):
            continue
        if _RDZV_COUNT >= f.at_rdzv:
            _apply(f, None, rank=rank)
