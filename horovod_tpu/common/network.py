"""Framed, HMAC-authenticated TCP messaging.

TPU-native stand-in for both of the reference's transports: the MPI
control plane (``MPI_Gather``/``MPI_Bcast`` each cycle, reference:
horovod/common/operations.cc:1044-1065,1249-1302) and the launcher's
cloudpickle ``Wire`` with HMAC-digest authentication (reference:
horovod/run/common/util/network.py:49-149).

Frame layout: ``u32 payload_len | u8 tag | payload``. When a secret key
is set, every frame carries a 32-byte HMAC-SHA256 of (tag|payload)
before the payload — unlike the reference, which HMACs only pickled
service messages, we authenticate the coordinator control plane too.
"""

from __future__ import annotations

import hmac
import hashlib
import random
import socket
import struct
import time
from typing import Callable, Iterator, Optional, Tuple

_HDR = struct.Struct("<IB")
_DIGEST_LEN = 32
# Below this size, frames go out as one concatenated sendall (one
# packet); above it the header and payload are sent separately so the
# payload never has to be copied into a fresh bytes object. Large frames
# are the data plane's hot path — on a CPU-bound host the avoided memcpy
# is a measurable fraction of per-op cost.
_INLINE_SEND = 16 * 1024

# MSG_ZEROCOPY send gate: frames with at least this many payload bytes
# go out via hvd_sendv_zc (kernel pins the pages; completions drained
# before return), smaller ones keep the plain copying sendmsg. < 0
# disables — the import-time default, so the module stands alone; the
# runtime arms it from Config.zerocopy_send_threshold
# (HOROVOD_TPU_ZEROCOPY_SEND_THRESHOLD) via set_zerocopy_threshold.
_ZC_THRESHOLD = -1
# Counter hooks rebound by attach_zerocopy_metrics (runtime metrics
# registry): sends that went out zero-copy, and completions where the
# kernel silently degraded to a copy (loopback always does).
_ZC_SENDS_METRIC = None
_ZC_COPIED_METRIC = None


def set_zerocopy_threshold(threshold: int) -> None:
    """Arm (or disarm with <= 0) the MSG_ZEROCOPY send threshold for
    every Channel in this process."""
    global _ZC_THRESHOLD
    _ZC_THRESHOLD = threshold if threshold > 0 else -1


def attach_zerocopy_metrics(sends, copied) -> None:
    """Bind the hvd_zerocopy_sends_total / hvd_zerocopy_copied_total
    counters (None detaches)."""
    global _ZC_SENDS_METRIC, _ZC_COPIED_METRIC
    _ZC_SENDS_METRIC = sends
    _ZC_COPIED_METRIC = copied


def zc_fanout_send(lib, fds, tag: int, payload,
                   secret_buf, secret_len: int,
                   timeout_ms: int = -1) -> bool:
    """MSG_ZEROCOPY leg for the coordinator fanout broadcast
    (_NativeFanout.send_all): a frame at/above the armed threshold
    goes out as one hvd_sendv_zc per peer — pages pinned once per
    send, completions drained before return, so the caller keeps the
    exact buffer-lifetime contract of hvd_broadcast_frame. Returns
    False when the gate is closed (threshold disarmed, frame too
    small, or a pre-zerocopy .so): the caller then keeps its single
    hvd_broadcast_frame call. Same wire bytes either way."""
    view = as_byte_view(payload)
    if _ZC_THRESHOLD < 0 or len(view) < _ZC_THRESHOLD \
            or not hasattr(lib, "hvd_sendv_zc"):
        return False
    import ctypes
    import numpy as np
    arr = np.frombuffer(view, np.uint8)  # zero-copy address probe
    ptrs = (ctypes.c_void_p * 1)(arr.ctypes.data)
    lens = (ctypes.c_int64 * 1)(len(arr))
    for fd in fds:
        zs = ctypes.c_int(0)
        zcopied = ctypes.c_int(0)
        rc = lib.hvd_sendv_zc(fd, tag, ptrs, lens, 1, secret_buf,
                              secret_len, timeout_ms,
                              ctypes.byref(zs), ctypes.byref(zcopied))
        if rc != 0:
            raise ConnectionError(
                f"zero-copy broadcast failed: errno {-rc}")
        if _ZC_SENDS_METRIC is not None and zs.value:
            _ZC_SENDS_METRIC.inc(zs.value)
        if _ZC_COPIED_METRIC is not None and zcopied.value:
            _ZC_COPIED_METRIC.inc(zcopied.value)
    return True


def as_byte_view(payload):
    """Flat byte view over any C-contiguous buffer; bytes pass through.
    Centralizes two portability guards: ``memoryview.cast`` rejects N-D
    zero-size views ("zeros in shape or strides"), so empty buffers
    normalize to ``b""``; and numpy extension dtypes (ml_dtypes
    bfloat16 and friends) don't speak the buffer protocol, so those
    arrays are reinterpreted as uint8 bytes first (a view, not a
    copy — writability is preserved for recv_into)."""
    if isinstance(payload, (bytes, bytearray)):
        return payload
    try:
        mv = memoryview(payload)
    except (ValueError, TypeError):
        import numpy as np
        if not getattr(payload, "flags", None) or \
                not payload.flags.c_contiguous:
            # an ascontiguousarray here would be a silent COPY —
            # receive paths would fill the copy and drop the data
            raise TypeError(
                "as_byte_view needs a C-contiguous buffer for "
                "extension-dtype arrays")
        # reshape(-1) first: a 0-d array can't change dtype, and the
        # reshape of a contiguous array is a view (writability kept)
        mv = memoryview(payload.reshape(-1).view(np.uint8))
    return mv.cast("B") if mv.nbytes else b""


def _recv_exact_into(sock: socket.socket, view: memoryview,
                     who: str = "peer", hb=None) -> None:
    """Fill ``view`` from ``sock``. ``who`` names the peer in every
    transport error. ``hb`` is an optional ``(timeout_s, interval_s,
    on_idle)`` liveness deadline: the wait is sliced into
    ``interval_s`` ticks (``on_idle`` fires per idle tick — the
    coordinator uses it to PING waiting workers) and TOTAL SILENCE for
    ``timeout_s`` raises — any received byte resets the clock, so a
    big frame trickling in over a slow link never false-positives."""
    got = 0
    n = len(view)
    if hb is None:
        while got < n:
            r = sock.recv_into(view[got:])
            if r == 0:
                raise ConnectionError(
                    f"connection to {who} closed while reading")
            got += r
        return
    timeout_s, interval_s, on_idle = hb
    idle = 0.0
    prev = sock.gettimeout()
    sock.settimeout(interval_s)
    try:
        while got < n:
            try:
                r = sock.recv_into(view[got:])
            except socket.timeout:
                idle += interval_s
                if on_idle is not None:
                    on_idle()
                if idle >= timeout_s:
                    raise ConnectionError(
                        f"no data from {who} for {idle:.0f}s — peer "
                        f"presumed dead (heartbeat timeout "
                        f"{timeout_s:g}s; raise "
                        f"HOROVOD_HEARTBEAT_TIMEOUT if peers "
                        f"legitimately stall longer)")
                continue
            if r == 0:
                raise ConnectionError(
                    f"connection to {who} closed while reading")
            got += r
            idle = 0.0
    finally:
        sock.settimeout(prev)


def _recv_exact(sock: socket.socket, n: int, who: str = "peer",
                hb=None) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf), who, hb)
    return bytes(buf)


class Channel:
    """One framed duplex connection (optionally HMAC-authenticated).

    ``peer`` labels the other end in every transport error ("rank 3
    (10.0.0.7:4921)" beats "socket closed"); controllers overwrite it
    with the peer's rank once the handshake reveals it. :meth:`arm`
    attaches a liveness deadline to all subsequent recvs."""

    def __init__(self, sock: socket.socket, secret: bytes = b"",
                 peer: Optional[str] = None):
        self.sock = sock
        self.secret = secret
        if peer is None:
            try:
                # AF_UNIX peers (socketpairs in tests) report a bare
                # string, often empty — no host:port to name.
                name = sock.getpeername()
                if isinstance(name, tuple) and len(name) >= 2:
                    peer = f"{name[0]}:{name[1]}"
                else:
                    peer = str(name) or "peer"
            except OSError:
                peer = "peer"
        self.peer = peer
        self._hb = None
        # ctypes copy of the (immutable) secret for the native wire
        # paths, built once — not per frame.
        self._c_secret = None
        # Don't batch small frames; collectives are latency-sensitive.
        # (No-op on non-TCP sockets, e.g. AF_UNIX socketpairs in tests.)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def arm(self, timeout_s: float, interval_s: float,
            on_idle: Optional[Callable[[], None]] = None) -> None:
        """Enable the recv liveness deadline: total silence from the
        peer for ``timeout_s`` fails the recv instead of blocking
        forever. ``on_idle`` runs once per ``interval_s`` idle tick.
        ``timeout_s <= 0`` disarms.

        Also sets SO_RCVTIMEO and SO_SNDTIMEO on the raw socket:

        * the native fanout (controller._NativeFanout) reads these
          same fds with blocking recv(2) once poll() reports
          readability, and a peer stalling MID-FRAME (header
          delivered, body never arrives) would otherwise block that
          read forever with the Python-level deadline unable to run;
        * a wedged-but-alive peer that stops DRAINING fills the TCP
          buffers and would block sendall/write_all forever — while
          stuck in send, this rank can't run its own recv deadline or
          fan an ABORT, so sends must be bounded too. The per-syscall
          timeout only fires after ``timeout_s`` with zero buffer
          progress; a live-but-slow reader keeps every send moving.

        Python's own sliced recv path is unaffected (settimeout
        switches the fd to non-blocking mode, where SO_RCVTIMEO is
        inert)."""
        if timeout_s and timeout_s > 0:
            # Clamp the slice to HALF the deadline: on_idle is how a
            # waiting sender beacons proof-of-life to ranks waiting on
            # *it*, and it must fire at least twice per peer deadline
            # window or an interval in (timeout/2, timeout] plus cycle
            # skew false-aborts a healthy world (the PING gate and the
            # native fanout slice cap enforce the same invariant).
            half = timeout_s / 2.0
            interval_s = min(interval_s, half) if interval_s > 0 \
                else half
            self._hb = (timeout_s, interval_s, on_idle)
            self._set_kernel_timeouts(timeout_s)
        else:
            self._hb = None
            self._set_kernel_timeouts(0.0)

    def _set_kernel_timeouts(self, timeout_s: float) -> None:
        sec = int(timeout_s)
        usec = int((timeout_s - sec) * 1e6)
        tv = struct.pack("ll", sec, usec)
        for opt in (socket.SO_RCVTIMEO, socket.SO_SNDTIMEO):
            try:
                self.sock.setsockopt(socket.SOL_SOCKET, opt, tv)
            except (OSError, struct.error):
                pass  # exotic socket: Python-level deadline still works

    def send(self, payload, tag: int = 0) -> None:
        """``payload`` is any C-contiguous buffer (bytes, bytearray,
        memoryview, numpy array) — large buffers are written straight
        from their memory, never copied into a bytes object. One
        framing implementation: a single-part vectored send."""
        self.sendv((payload,), tag)

    def sendv(self, parts, tag: int = 0) -> None:
        """Vectored framed send — THE framing implementation every
        outbound frame uses: ``parts`` (C-contiguous buffers) ship as
        ONE frame without ever being concatenated. Above the inline
        threshold the native core sends header + HMAC + all parts in
        ONE sendmsg(2) with the GIL released (hvd_sendv); below it the
        whole frame goes out as one small sendall. The bytes on the
        wire are identical on every path."""
        views = [as_byte_view(p) for p in parts]
        total = sum(len(v) for v in views)
        if total > _INLINE_SEND and self._sendv_native(views, total,
                                                      tag):
            return
        hdr = _HDR.pack(total, tag)
        if self.secret:
            h = hmac.new(self.secret, bytes((tag,)), hashlib.sha256)
            for v in views:
                h.update(v)
            head = hdr + h.digest()
        else:
            head = hdr
        if total <= _INLINE_SEND:
            # Small frames (control plane) in one packet-sized write.
            self.sock.sendall(b"".join([head, *views]))
            return
        self.sock.sendall(head)
        for v in views:
            if len(v):
                self.sock.sendall(v)

    def _sendv_native(self, views, total: int, tag: int) -> bool:
        """One-sendmsg frame write via hvd_sendv; False => caller uses
        the Python path (no native core, or an exotic buffer)."""
        from horovod_tpu import native as _native
        lib = _native.get()
        if lib is None:
            return False
        import ctypes
        import numpy as np
        n = len(views)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_int64 * n)()
        keep = []  # hold the zero-copy wrappers behind the pointers
        for i, v in enumerate(views):
            ln = len(v)
            lens[i] = ln
            if ln == 0:
                ptrs[i] = None
                continue
            arr = np.frombuffer(v, np.uint8)  # zero-copy address probe
            keep.append(arr)
            ptrs[i] = arr.ctypes.data
        if _ZC_THRESHOLD >= 0 and total >= _ZC_THRESHOLD \
                and hasattr(lib, "hvd_sendv_zc"):
            # MSG_ZEROCOPY leg: same frame bytes, pages pinned instead
            # of copied; the native call drains every completion before
            # returning (bounded by the armed deadline), so ``keep``
            # may be dropped the moment it returns — exactly the
            # lifetime contract of the plain path.
            hb = self._hb
            timeout_ms = int(hb[0] * 1000) if hb else -1
            zs = ctypes.c_int(0)
            zcopied = ctypes.c_int(0)
            rc = lib.hvd_sendv_zc(
                self.sock.fileno(), tag, ptrs, lens, n,
                self._secret_buf(), len(self.secret or b""),
                timeout_ms, ctypes.byref(zs), ctypes.byref(zcopied))
            if rc != 0:
                raise ConnectionError(
                    f"send to {self.peer} failed: errno {-rc}")
            if _ZC_SENDS_METRIC is not None and zs.value:
                _ZC_SENDS_METRIC.inc(zs.value)
            if _ZC_COPIED_METRIC is not None and zcopied.value:
                _ZC_COPIED_METRIC.inc(zcopied.value)
            return True
        rc = lib.hvd_sendv(self.sock.fileno(), tag, ptrs, lens, n,
                           self._secret_buf(), len(self.secret or b""))
        if rc != 0:
            raise ConnectionError(
                f"send to {self.peer} failed: errno {-rc}")
        return True

    def recv(self) -> Tuple[int, bytes]:
        who, hb = self.peer, self._hb
        hdr = _recv_exact(self.sock, _HDR.size, who, hb)
        n, tag = _HDR.unpack(hdr)
        if self.secret:
            digest = _recv_exact(self.sock, _DIGEST_LEN, who, hb)
            payload = _recv_exact(self.sock, n, who, hb)
            expected = hmac.new(self.secret, bytes([tag]) + payload,
                                hashlib.sha256).digest()
            if not hmac.compare_digest(digest, expected):
                raise ConnectionError(
                    f"HMAC authentication failed for frame from {who}")
            return tag, payload
        payload = _recv_exact(self.sock, n, who, hb)
        return tag, payload

    def recv_into(self, buf) -> Tuple[int, int]:
        """Receive one frame directly into a writable buffer (zero-copy
        data-plane path; ops/ring.py, the controller *_into
        primitives). The frame must fit exactly or be smaller. Returns
        (tag, payload_nbytes)."""
        tag, n, spill = self.recv_into_spill(buf)
        if spill is not None:
            raise ConnectionError(
                f"frame of {n} bytes from {self.peer} overflows "
                f"{len(as_byte_view(buf))}-byte buffer")
        return tag, n

    def recv_into_spill(self, buf):
        """Like :meth:`recv_into`, but a frame LARGER than ``buf``
        comes back whole as bytes instead of raising: returns
        (tag, payload_nbytes, spill) with ``spill`` None when the
        payload landed in ``buf``. The controller *_into primitives
        need this: out-of-band frames (PING/METRICS/ABORT) share the
        channel with data payloads and may exceed the preallocated
        destination — an ABORT notice in particular must survive to
        be decoded, not die as an overflow error."""
        who, hb = self.peer, self._hb
        if hb is None or hb[2] is None:
            # No idle beacon to run from Python: the whole recv
            # (header, digest, payload, HMAC check) can run in ONE
            # native call with the GIL released. With an on_idle
            # callback armed (coordinator channels PING per idle
            # slice), stay on the sliced Python path.
            res = self._recv_into_native(buf, who, hb)
            if res is not None:
                return res
        hdr = _recv_exact(self.sock, _HDR.size, who, hb)
        n, tag = _HDR.unpack(hdr)
        view = memoryview(as_byte_view(buf))
        if n > len(view):
            if self.secret:
                digest = _recv_exact(self.sock, _DIGEST_LEN, who, hb)
                payload = _recv_exact(self.sock, n, who, hb)
                h = hmac.new(self.secret, bytes((tag,)) + payload,
                             hashlib.sha256)
                if not hmac.compare_digest(digest, h.digest()):
                    raise ConnectionError(
                        f"HMAC authentication failed for frame from "
                        f"{who}")
            else:
                payload = _recv_exact(self.sock, n, who, hb)
            return tag, n, payload
        if self.secret:
            digest = _recv_exact(self.sock, _DIGEST_LEN, who, hb)
            _recv_exact_into(self.sock, view[:n], who, hb)
            h = hmac.new(self.secret, bytes((tag,)), hashlib.sha256)
            h.update(view[:n])
            if not hmac.compare_digest(digest, h.digest()):
                raise ConnectionError(
                    f"HMAC authentication failed for frame from {who}")
        else:
            _recv_exact_into(self.sock, view[:n], who, hb)
        return tag, n, None

    def _secret_buf(self):
        """ctypes u8 buffer of the channel secret, built once (the
        secret is immutable for the channel's lifetime)."""
        if self._c_secret is None:
            import ctypes
            secret = self.secret or b""
            # hvdlint: owned-by=main -- channel-confined lazy init: a Channel is serviced by one thread at a time, and the buffer is rebuilt identically from the immutable secret
            self._c_secret = (
                ctypes.c_uint8 * max(1, len(secret))).from_buffer_copy(
                secret or b"\x00")
        return self._c_secret

    def _recv_into_native(self, buf, who: str, hb):
        """hvd_recv_into fast path for :meth:`recv_into`; None =>
        caller runs the Python path. Error messages mirror the Python
        path's so failure handling stays uniform."""
        from horovod_tpu import native as _native
        lib = _native.get()
        if lib is None:
            return None
        import ctypes
        import numpy as np
        view = as_byte_view(buf)
        cap = len(view)
        arr = np.frombuffer(view, np.uint8) if cap else None
        secret = self.secret or b""
        sec = self._secret_buf()
        if hb is None:
            timeout_ms = interval_ms = -1
        else:
            timeout_ms = max(1, int(hb[0] * 1000))
            interval_ms = max(1, int(hb[1] * 1000))
        out_len = ctypes.c_int64()
        out_tag = ctypes.c_uint8()
        spill = ctypes.POINTER(ctypes.c_uint8)()
        rc = lib.hvd_recv_into(
            self.sock.fileno(), sec, len(secret),
            arr.ctypes.data if arr is not None else None, cap,
            None, 0, ctypes.byref(out_len), ctypes.byref(out_tag),
            timeout_ms, interval_ms, ctypes.byref(spill))
        if rc == 0:
            return out_tag.value, out_len.value, None
        if rc == 1:
            try:
                payload = ctypes.string_at(spill, out_len.value)
            finally:
                lib.hvd_free(spill)
            return out_tag.value, out_len.value, payload
        import errno as _errno
        if rc == -_errno.ETIMEDOUT:
            raise ConnectionError(
                f"no data from {who} for {hb[0]:.0f}s — peer presumed "
                f"dead (heartbeat timeout {hb[0]:g}s; raise "
                f"HOROVOD_HEARTBEAT_TIMEOUT if peers legitimately "
                f"stall longer)")
        if rc == -_errno.EBADMSG:
            raise ConnectionError(
                f"HMAC authentication failed for frame from {who}")
        if rc == -_errno.ECONNRESET:
            raise ConnectionError(
                f"connection to {who} closed while reading")
        raise ConnectionError(
            f"recv from {who} failed: errno {-rc}")

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def backoff_delays(base: float = 0.05, cap: float = 1.0,
                   factor: float = 2.0, jitter: float = 0.25,
                   rng: Optional[Callable[[], float]] = None
                   ) -> Iterator[float]:
    """Capped exponential backoff with multiplicative jitter: ``base``,
    ``base*factor``, ... clamped to ``cap``, each scaled by a uniform
    factor in [1-jitter, 1+jitter] so a herd of ranks retrying against
    one listener (world startup, ring rendezvous) never stampedes in
    lockstep. ``rng`` is injectable for deterministic tests."""
    if rng is None:
        rng = random.random
    delay = base
    while True:
        yield min(cap, delay) * (1.0 + jitter * (2.0 * rng() - 1.0))
        delay = min(cap, delay * factor)


def connect(addr: str, port: int, secret: bytes = b"",
            timeout: Optional[float] = None,
            retry_deadline: Optional[float] = None) -> Channel:
    """Connect with exponential-backoff retries until ``retry_deadline``
    (seconds of budget), mirroring the reference client's probing/retry
    loop (reference: run/common/util/network.py:152-246)."""
    deadline = (time.monotonic() + retry_deadline
                if retry_deadline is not None else None)
    last_err: Optional[Exception] = None
    delays = backoff_delays()
    attempts = 0
    while True:
        try:
            attempts += 1
            sock = socket.create_connection((addr, port), timeout=timeout)
            # The connect timeout must not linger as a recv timeout: the
            # steady-state worker blocks in recv() for a whole cycle, which
            # can legitimately exceed it (slow rank, long XLA compile).
            sock.settimeout(None)
            return Channel(sock, secret, peer=f"{addr}:{port}")
        except OSError as e:
            last_err = e
            now = time.monotonic()
            if deadline is None or now >= deadline:
                raise ConnectionError(
                    f"Could not connect to {addr}:{port} after "
                    f"{attempts} attempt(s): {last_err}")
            time.sleep(min(next(delays), max(0.0, deadline - now)))


def listen(port: int = 0, host: str = "") -> socket.socket:
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(128)
    return srv
