"""Framed, HMAC-authenticated TCP messaging.

TPU-native stand-in for both of the reference's transports: the MPI
control plane (``MPI_Gather``/``MPI_Bcast`` each cycle, reference:
horovod/common/operations.cc:1044-1065,1249-1302) and the launcher's
cloudpickle ``Wire`` with HMAC-digest authentication (reference:
horovod/run/common/util/network.py:49-149).

Frame layout: ``u32 payload_len | u8 tag | payload``. When a secret key
is set, every frame carries a 32-byte HMAC-SHA256 of (tag|payload)
before the payload — unlike the reference, which HMACs only pickled
service messages, we authenticate the coordinator control plane too.
"""

from __future__ import annotations

import hmac
import hashlib
import socket
import struct
from typing import Optional, Tuple

_HDR = struct.Struct("<IB")
_DIGEST_LEN = 32
# Below this size, frames go out as one concatenated sendall (one
# packet); above it the header and payload are sent separately so the
# payload never has to be copied into a fresh bytes object. Large frames
# are the data plane's hot path — on a CPU-bound host the avoided memcpy
# is a measurable fraction of per-op cost.
_INLINE_SEND = 16 * 1024


def as_byte_view(payload):
    """Flat byte view over any C-contiguous buffer; bytes pass through.
    Centralizes two portability guards: ``memoryview.cast`` rejects N-D
    zero-size views ("zeros in shape or strides"), so empty buffers
    normalize to ``b""``; and numpy extension dtypes (ml_dtypes
    bfloat16 and friends) don't speak the buffer protocol, so those
    arrays are reinterpreted as uint8 bytes first (a view, not a
    copy — writability is preserved for recv_into)."""
    if isinstance(payload, (bytes, bytearray)):
        return payload
    try:
        mv = memoryview(payload)
    except (ValueError, TypeError):
        import numpy as np
        if not getattr(payload, "flags", None) or \
                not payload.flags.c_contiguous:
            # an ascontiguousarray here would be a silent COPY —
            # receive paths would fill the copy and drop the data
            raise TypeError(
                "as_byte_view needs a C-contiguous buffer for "
                "extension-dtype arrays")
        # reshape(-1) first: a 0-d array can't change dtype, and the
        # reshape of a contiguous array is a view (writability kept)
        mv = memoryview(payload.reshape(-1).view(np.uint8))
    return mv.cast("B") if mv.nbytes else b""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise ConnectionError("socket closed while reading")
        got += r
    return bytes(buf)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    n = len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise ConnectionError("socket closed while reading")
        got += r


class Channel:
    """One framed duplex connection (optionally HMAC-authenticated)."""

    def __init__(self, sock: socket.socket, secret: bytes = b""):
        self.sock = sock
        self.secret = secret
        # Don't batch small frames; collectives are latency-sensitive.
        # (No-op on non-TCP sockets, e.g. AF_UNIX socketpairs in tests.)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def send(self, payload, tag: int = 0) -> None:
        """``payload`` is any C-contiguous buffer (bytes, bytearray,
        memoryview, numpy array) — large buffers are written straight
        from their memory, never copied into a bytes object."""
        payload = as_byte_view(payload)
        n = len(payload)
        hdr = _HDR.pack(n, tag)
        if self.secret:
            h = hmac.new(self.secret, bytes((tag,)), hashlib.sha256)
            h.update(payload)
            digest = h.digest()
            if n <= _INLINE_SEND:
                self.sock.sendall(b"".join((hdr, digest, payload)))
            else:
                self.sock.sendall(hdr + digest)
                self.sock.sendall(payload)
        elif n <= _INLINE_SEND:
            self.sock.sendall(b"".join((hdr, payload)))
        else:
            self.sock.sendall(hdr)
            self.sock.sendall(payload)

    def recv(self) -> Tuple[int, bytes]:
        hdr = _recv_exact(self.sock, _HDR.size)
        n, tag = _HDR.unpack(hdr)
        if self.secret:
            digest = _recv_exact(self.sock, _DIGEST_LEN)
            payload = _recv_exact(self.sock, n)
            expected = hmac.new(self.secret, bytes([tag]) + payload,
                                hashlib.sha256).digest()
            if not hmac.compare_digest(digest, expected):
                raise ConnectionError("HMAC authentication failed")
            return tag, payload
        payload = _recv_exact(self.sock, n)
        return tag, payload

    def recv_into(self, buf) -> Tuple[int, int]:
        """Receive one frame directly into a writable buffer (zero-copy
        data-plane path; ops/ring.py). The frame must fit exactly or be
        smaller. Returns (tag, payload_nbytes)."""
        hdr = _recv_exact(self.sock, _HDR.size)
        n, tag = _HDR.unpack(hdr)
        view = memoryview(as_byte_view(buf))
        if n > len(view):
            raise ConnectionError(
                f"frame of {n} bytes overflows {len(view)}-byte buffer")
        if self.secret:
            digest = _recv_exact(self.sock, _DIGEST_LEN)
            _recv_exact_into(self.sock, view[:n])
            h = hmac.new(self.secret, bytes((tag,)), hashlib.sha256)
            h.update(view[:n])
            if not hmac.compare_digest(digest, h.digest()):
                raise ConnectionError("HMAC authentication failed")
        else:
            _recv_exact_into(self.sock, view[:n])
        return tag, n

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def connect(addr: str, port: int, secret: bytes = b"",
            timeout: Optional[float] = None,
            retry_deadline: Optional[float] = None) -> Channel:
    """Connect with retries until ``retry_deadline`` (seconds of budget),
    mirroring the reference client's probing/retry loop
    (reference: run/common/util/network.py:152-246)."""
    import time
    deadline = (time.monotonic() + retry_deadline
                if retry_deadline is not None else None)
    last_err: Optional[Exception] = None
    while True:
        try:
            sock = socket.create_connection((addr, port), timeout=timeout)
            # The connect timeout must not linger as a recv timeout: the
            # steady-state worker blocks in recv() for a whole cycle, which
            # can legitimately exceed it (slow rank, long XLA compile).
            sock.settimeout(None)
            return Channel(sock, secret)
        except OSError as e:
            last_err = e
            if deadline is None or time.monotonic() >= deadline:
                raise ConnectionError(
                    f"Could not connect to {addr}:{port}: {last_err}")
            time.sleep(0.05)


def listen(port: int = 0, host: str = "") -> socket.socket:
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(128)
    return srv
