"""Operation status type.

Equivalent of the reference's ``horovod::common::Status``
(reference: horovod/common/common.h:70-121): OK / UNKNOWN_ERROR /
PRECONDITION_ERROR / ABORTED / INVALID_ARGUMENT / IN_PROGRESS, carried
through enqueue callbacks and the handle manager.
"""

from __future__ import annotations

import enum
from typing import Optional


class StatusType(enum.IntEnum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


class Status:
    __slots__ = ("type", "reason", "aborted_by")

    def __init__(self, type_: StatusType = StatusType.OK, reason: str = "",
                 aborted_by: "Optional[int]" = None):
        self.type = type_
        self.reason = reason
        # Global rank the world abort originated from (None for plain
        # shutdowns) — lets handle APIs raise WorldAbortedError with
        # the failed rank attached instead of a generic internal error.
        self.aborted_by = aborted_by

    @staticmethod
    def OK() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def UnknownError(msg: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def PreconditionError(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def Aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def WorldAborted(origin_rank: int, cause: str) -> "Status":
        return Status(StatusType.ABORTED,
                      world_abort_message(origin_rank, cause),
                      aborted_by=origin_rank)

    @staticmethod
    def InvalidArgument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def InProgress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    def ok(self) -> bool:
        return self.type == StatusType.OK

    def in_progress(self) -> bool:
        return self.type == StatusType.IN_PROGRESS

    def __repr__(self) -> str:
        return f"Status({self.type.name}, {self.reason!r})"


class HorovodInternalError(RuntimeError):
    """Raised to user code when a collective fails (coordinator ERROR
    response or shutdown; reference: message.h Response::ERROR and
    operations.cc:898-913 SHUT_DOWN_ERROR fan-out)."""


def world_abort_message(origin_rank: int, cause: str) -> str:
    origin = (f"rank {origin_rank}" if origin_rank is not None
              and origin_rank >= 0 else "unknown rank")
    return f"Horovod world aborted (origin: {origin}): {cause}"


class WorldAbortedError(HorovodInternalError):
    """The world was torn down by the fail-fast abort protocol: some
    rank died, a transport failed, or the stall-shutdown threshold
    fired, and the coordinator fanned an ABORT to every survivor.
    Subclasses HorovodInternalError so existing error handling keeps
    working; carries the originating rank and bare cause so survivors
    can log or react to *which* peer failed — and so relaying the
    abort re-wraps the cause exactly once, not per hop."""

    def __init__(self, message: str, origin_rank: int = -1,
                 cause: "Optional[str]" = None):
        super().__init__(message)
        self.origin_rank = origin_rank
        self.cause = cause if cause is not None else message


SHUT_DOWN_ERROR = (
    "Horovod has been shut down. This was caused by an exception on one of "
    "the ranks or an attempt to run a collective after shutdown was called."
)

DUPLICATE_NAME_ERROR_FMT = (
    "Requested to %s a tensor with the same name as another tensor that is "
    "currently being processed. If you want to request another tensor, use "
    "a different tensor name. Tensor name: %s"
)
