"""Coordinator message protocol: Request / RequestList / Response / ResponseList.

Python mirror of the reference's message layer
(reference: horovod/common/message.h:45-185, message.cc, wire/message.fbs).
The reference serializes with FlatBuffers; we use a purpose-built
little-endian binary wire format (see `wire.py`) that the native C++ core
(horovod_tpu/native) reads and writes with the identical layout, so the
control plane can mix Python and C++ endpoints.

Differences from the reference, by design:
- dtype set adds BFLOAT16 (the TPU-native wire/accumulate type).
- op set adds ALLTOALL, REDUCESCATTER, BARRIER and JOIN — native TPU
  extensions the reference gained only in later versions.
"""

from __future__ import annotations

import enum
from typing import List, Sequence

import numpy as np


class DataType(enum.IntEnum):
    """Tensor element types (reference: message.h:29-41 DataType)."""
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10  # TPU extension


_NP_TO_DT = {
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
    np.dtype(np.bool_): DataType.BOOL,
}

_DT_SIZE = {
    DataType.UINT8: 1, DataType.INT8: 1,
    DataType.UINT16: 2, DataType.INT16: 2,
    DataType.INT32: 4, DataType.INT64: 8,
    DataType.FLOAT16: 2, DataType.FLOAT32: 4, DataType.FLOAT64: 8,
    DataType.BOOL: 1, DataType.BFLOAT16: 2,
}


def numpy_dtype_to_datatype(dtype) -> DataType:
    dtype = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    # ml_dtypes bfloat16 registers as a numpy extension dtype.
    if dtype.name == "bfloat16":
        return DataType.BFLOAT16
    try:
        return _NP_TO_DT[dtype]
    except KeyError:
        raise ValueError(f"Unsupported dtype for horovod_tpu: {dtype}")


def datatype_to_numpy_dtype(dt: DataType):
    if dt == DataType.BFLOAT16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    for np_dt, d in _NP_TO_DT.items():
        if d == dt:
            return np_dt
    raise ValueError(f"Unknown DataType {dt}")


def datatype_size(dt: DataType) -> int:
    return _DT_SIZE[dt]


def datatype_name(dt: DataType) -> str:
    return DataType(dt).name.lower()


class RequestType(enum.IntEnum):
    """(reference: message.h:48-52 Request::RequestType)"""
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    # TPU-native extensions:
    ALLTOALL = 3
    REDUCESCATTER = 4
    BARRIER = 5
    JOIN = 6


class ResponseType(enum.IntEnum):
    """(reference: message.h:133-138 Response::ResponseType)"""
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    ALLTOALL = 3
    REDUCESCATTER = 4
    BARRIER = 5
    JOIN = 6
    ERROR = 7


class Request:
    """A rank's announcement that one named tensor is ready
    (reference: message.h:45-98)."""

    __slots__ = ("request_rank", "request_type", "tensor_type",
                 "tensor_name", "root_rank", "device", "tensor_shape",
                 "prescale_factor", "postscale_factor")

    def __init__(self, request_rank: int = 0,
                 request_type: RequestType = RequestType.ALLREDUCE,
                 tensor_type: DataType = DataType.FLOAT32,
                 tensor_name: str = "",
                 root_rank: int = -1,
                 device: int = -1,
                 tensor_shape: Sequence[int] = (),
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0):
        self.request_rank = request_rank
        self.request_type = RequestType(request_type)
        self.tensor_type = DataType(tensor_type)
        self.tensor_name = tensor_name
        self.root_rank = root_rank
        self.device = device
        self.tensor_shape = tuple(int(d) for d in tensor_shape)
        self.prescale_factor = prescale_factor
        self.postscale_factor = postscale_factor

    def __eq__(self, other):
        return (isinstance(other, Request) and
                all(getattr(self, s) == getattr(other, s)
                    for s in Request.__slots__))

    def __repr__(self):
        return (f"Request({self.request_type.name}, rank={self.request_rank},"
                f" name={self.tensor_name!r}, dtype={self.tensor_type.name},"
                f" shape={self.tensor_shape}, root={self.root_rank},"
                f" device={self.device})")


class RequestList:
    """One cycle's worth of requests from a rank, plus the shutdown bit
    (reference: message.h:100-123)."""

    __slots__ = ("requests", "shutdown")

    def __init__(self, requests: List[Request] | None = None,
                 shutdown: bool = False):
        self.requests = requests if requests is not None else []
        self.shutdown = shutdown

    def add_request(self, req: Request) -> None:
        self.requests.append(req)

    def __eq__(self, other):
        return (isinstance(other, RequestList)
                and self.shutdown == other.shutdown
                and self.requests == other.requests)


class Response:
    """Coordinator's verdict for one (possibly fused) set of tensors
    (reference: message.h:130-185)."""

    __slots__ = ("response_type", "tensor_names", "error_message",
                 "devices", "tensor_sizes", "prescale_factor",
                 "postscale_factor")

    def __init__(self, response_type: ResponseType = ResponseType.ALLREDUCE,
                 tensor_names: List[str] | None = None,
                 error_message: str = "",
                 devices: List[int] | None = None,
                 tensor_sizes: List[int] | None = None,
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0):
        self.response_type = ResponseType(response_type)
        self.tensor_names = tensor_names if tensor_names is not None else []
        self.error_message = error_message
        self.devices = devices if devices is not None else []
        self.tensor_sizes = tensor_sizes if tensor_sizes is not None else []
        self.prescale_factor = prescale_factor
        self.postscale_factor = postscale_factor

    def add_tensor_name(self, name: str) -> None:
        self.tensor_names.append(name)

    def add_tensor_size(self, size: int) -> None:
        self.tensor_sizes.append(size)

    def __eq__(self, other):
        return (isinstance(other, Response) and
                all(getattr(self, s) == getattr(other, s)
                    for s in Response.__slots__))

    def __repr__(self):
        return (f"Response({self.response_type.name},"
                f" names={self.tensor_names},"
                f" err={self.error_message!r})")


class ResponseList:
    """One cycle's broadcast from the coordinator: ordered, fused responses
    + shutdown bit (reference: message.h:187-214), plus the autotuner's
    currently tuned parameters so workers track the coordinator — the
    wire-level stand-in for the reference's MPI struct param sync
    (reference: parameter_manager.cc:64-78 SyncParams). Zero = untuned.
    """

    __slots__ = ("responses", "shutdown", "tuned_cycle_time_ms",
                 "tuned_fusion_threshold_bytes")

    def __init__(self, responses: List[Response] | None = None,
                 shutdown: bool = False,
                 tuned_cycle_time_ms: float = 0.0,
                 tuned_fusion_threshold_bytes: int = 0):
        self.responses = responses if responses is not None else []
        self.shutdown = shutdown
        self.tuned_cycle_time_ms = tuned_cycle_time_ms
        self.tuned_fusion_threshold_bytes = tuned_fusion_threshold_bytes

    def add_response(self, resp: Response) -> None:
        self.responses.append(resp)

    def __eq__(self, other):
        return (isinstance(other, ResponseList)
                and self.shutdown == other.shutdown
                and self.tuned_cycle_time_ms == other.tuned_cycle_time_ms
                and self.tuned_fusion_threshold_bytes
                    == other.tuned_fusion_threshold_bytes
                and self.responses == other.responses)
