"""Coordinator message protocol: Request / RequestList / Response / ResponseList.

Python mirror of the reference's message layer
(reference: horovod/common/message.h:45-185, message.cc, wire/message.fbs).
The reference serializes with FlatBuffers; we use a purpose-built
little-endian binary wire format (see `wire.py`) that the native C++ core
(horovod_tpu/native) reads and writes with the identical layout, so the
control plane can mix Python and C++ endpoints.

Differences from the reference, by design:
- dtype set adds BFLOAT16 (the TPU-native wire/accumulate type).
- op set adds ALLTOALL, REDUCESCATTER, BARRIER and JOIN — native TPU
  extensions the reference gained only in later versions.
"""

from __future__ import annotations

import enum
from typing import List, Sequence

import numpy as np


class DataType(enum.IntEnum):
    """Tensor element types (reference: message.h:29-41 DataType)."""
    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10  # TPU extension


_NP_TO_DT = {
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.uint16): DataType.UINT16,
    np.dtype(np.int16): DataType.INT16,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int64): DataType.INT64,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
    np.dtype(np.bool_): DataType.BOOL,
}

_DT_SIZE = {
    DataType.UINT8: 1, DataType.INT8: 1,
    DataType.UINT16: 2, DataType.INT16: 2,
    DataType.INT32: 4, DataType.INT64: 8,
    DataType.FLOAT16: 2, DataType.FLOAT32: 4, DataType.FLOAT64: 8,
    DataType.BOOL: 1, DataType.BFLOAT16: 2,
}


def numpy_dtype_to_datatype(dtype) -> DataType:
    # Hot path: one submission per tensor per step lands here. The
    # common dtypes hit the dict directly; computing ``dtype.name``
    # (a string-building numpy property) is deferred to the miss path,
    # where ml_dtypes bfloat16 — a numpy extension dtype — is resolved
    # and then memoized so it too becomes a dict hit.
    dtype = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    try:
        return _NP_TO_DT[dtype]
    except KeyError:
        pass
    if dtype.name == "bfloat16":
        _NP_TO_DT[dtype] = DataType.BFLOAT16
        return DataType.BFLOAT16
    raise ValueError(f"Unsupported dtype for horovod_tpu: {dtype}")


def datatype_to_numpy_dtype(dt: DataType):
    if dt == DataType.BFLOAT16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    for np_dt, d in _NP_TO_DT.items():
        if d == dt:
            return np_dt
    raise ValueError(f"Unknown DataType {dt}")


def datatype_size(dt: DataType) -> int:
    return _DT_SIZE[dt]


def datatype_name(dt: DataType) -> str:
    return DataType(dt).name.lower()


class RequestType(enum.IntEnum):
    """(reference: message.h:48-52 Request::RequestType)"""
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    # TPU-native extensions:
    ALLTOALL = 3
    REDUCESCATTER = 4
    BARRIER = 5
    JOIN = 6


class ResponseType(enum.IntEnum):
    """(reference: message.h:133-138 Response::ResponseType)"""
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    ALLTOALL = 3
    REDUCESCATTER = 4
    BARRIER = 5
    JOIN = 6
    ERROR = 7


class Request:
    """A rank's announcement that one named tensor is ready
    (reference: message.h:45-98)."""

    __slots__ = ("request_rank", "request_type", "tensor_type",
                 "tensor_name", "root_rank", "device", "tensor_shape",
                 "prescale_factor", "postscale_factor", "wire_dtype")

    def __init__(self, request_rank: int = 0,
                 request_type: RequestType = RequestType.ALLREDUCE,
                 tensor_type: DataType = DataType.FLOAT32,
                 tensor_name: str = "",
                 root_rank: int = -1,
                 device: int = -1,
                 tensor_shape: Sequence[int] = (),
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0,
                 wire_dtype: int = 0):
        self.request_rank = request_rank
        # Enum() calls dominate a hot enqueue burst's Request inits;
        # skip the re-wrap when the caller already passed the enum.
        self.request_type = request_type \
            if type(request_type) is RequestType \
            else RequestType(request_type)
        self.tensor_type = tensor_type \
            if type(tensor_type) is DataType \
            else DataType(tensor_type)
        self.tensor_name = tensor_name
        self.root_rank = root_rank
        self.device = device
        self.tensor_shape = tuple(int(d) for d in tensor_shape)
        self.prescale_factor = prescale_factor
        self.postscale_factor = postscale_factor
        # Proposed wire dtype (common/wire_dtype.py WIRE_* codes): this
        # rank's bid for on-the-wire compression of this tensor. The
        # coordinator resolves the world's common denominator and
        # broadcasts the verdict in Response.wire_dtype — negotiated
        # exactly like the fusion threshold, so heterogeneous knobs
        # degrade instead of diverging.
        self.wire_dtype = wire_dtype

    def __eq__(self, other):
        return (isinstance(other, Request) and
                all(getattr(self, s) == getattr(other, s)
                    for s in Request.__slots__))

    def __repr__(self):
        return (f"Request({self.request_type.name}, rank={self.request_rank},"
                f" name={self.tensor_name!r}, dtype={self.tensor_type.name},"
                f" shape={self.tensor_shape}, root={self.root_rank},"
                f" device={self.device})")


class RequestList:
    """One cycle's worth of requests from a rank, plus the shutdown bit
    (reference: message.h:100-123)."""

    __slots__ = ("requests", "shutdown")

    def __init__(self, requests: List[Request] | None = None,
                 shutdown: bool = False):
        self.requests = requests if requests is not None else []
        self.shutdown = shutdown

    def add_request(self, req: Request) -> None:
        self.requests.append(req)

    def __eq__(self, other):
        return (isinstance(other, RequestList)
                and self.shutdown == other.shutdown
                and self.requests == other.requests)


class Response:
    """Coordinator's verdict for one (possibly fused) set of tensors
    (reference: message.h:130-185)."""

    __slots__ = ("response_type", "tensor_names", "error_message",
                 "devices", "tensor_sizes", "prescale_factor",
                 "postscale_factor", "wire_dtype", "algorithm")

    def __init__(self, response_type: ResponseType = ResponseType.ALLREDUCE,
                 tensor_names: List[str] | None = None,
                 error_message: str = "",
                 devices: List[int] | None = None,
                 tensor_sizes: List[int] | None = None,
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0,
                 wire_dtype: int = 0,
                 algorithm: int = 0):
        self.response_type = ResponseType(response_type)
        self.tensor_names = tensor_names if tensor_names is not None else []
        self.error_message = error_message
        self.devices = devices if devices is not None else []
        self.tensor_sizes = tensor_sizes if tensor_sizes is not None else []
        self.prescale_factor = prescale_factor
        self.postscale_factor = postscale_factor
        # The coordinator's world-coherent verdicts for this (possibly
        # fused) batch: wire_dtype = resolved WIRE_* compression every
        # rank applies symmetrically on the data plane; algorithm =
        # stamped ALG_* route (default keeps each backend's own
        # size-based heuristics). Broadcast with the response, cached
        # with it, replayed with it.
        self.wire_dtype = wire_dtype
        self.algorithm = algorithm

    def add_tensor_name(self, name: str) -> None:
        self.tensor_names.append(name)

    def add_tensor_size(self, size: int) -> None:
        self.tensor_sizes.append(size)

    def __eq__(self, other):
        return (isinstance(other, Response) and
                all(getattr(self, s) == getattr(other, s)
                    for s in Response.__slots__))

    def __repr__(self):
        return (f"Response({self.response_type.name},"
                f" names={self.tensor_names},"
                f" err={self.error_message!r})")


class CacheCycleRequest:
    """One rank's cycle frame on the steady-state fast path (cache
    coherence wire message; upstream analog: the bit-vector +
    uncached-request message the response cache rides). ``hit_mask``
    has one bit per response-cache slot this rank queued this cycle
    with an unchanged signature; ``invalid_mask`` marks slots whose
    name was re-queued with a CHANGED signature (shape/dtype/...) and
    must be evicted world-wide; ``requests`` carries the uncached
    remainder as plain Requests. ``epoch`` is the sender's cache
    event-counter — the coordinator fails fast on any mismatch rather
    than let diverged caches grant mismatched collectives."""

    __slots__ = ("epoch", "nslots", "hit_mask", "invalid_mask",
                 "requests", "shutdown", "spec_payload")

    def __init__(self, epoch: int = 0, nslots: int = 0,
                 hit_mask: int = 0, invalid_mask: int = 0,
                 requests: List[Request] | None = None,
                 shutdown: bool = False,
                 spec_payload=None):
        self.epoch = epoch
        self.nslots = nslots
        self.hit_mask = hit_mask
        self.invalid_mask = invalid_mask
        self.requests = requests if requests is not None else []
        self.shutdown = shutdown
        # Fused speculative cycle (steady-state single-round fast
        # path): [(DataType, buffer), ...] — one pre-packed fused
        # allreduce buffer per replay-plan batch, in plan order. None
        # on a plain bitmask frame.
        self.spec_payload = spec_payload

    def __eq__(self, other):
        return (isinstance(other, CacheCycleRequest) and
                all(getattr(self, s) == getattr(other, s)
                    for s in ("epoch", "nslots", "hit_mask",
                              "invalid_mask", "requests", "shutdown"))
                and _payloads_equal(self.spec_payload,
                                    other.spec_payload))


class CacheCycleResponse:
    """The coordinator's cycle verdict on the fast path: ``grant_mask``
    = AND of every rank's hit bits (minus invalidated slots) — the
    tensors the whole world queued this cycle, replayed locally from
    the cache in ascending slot order; ``invalid_mask`` = OR of every
    rank's invalidate bits, evicted on every rank this cycle;
    ``response_list`` carries whatever negotiated the slow way
    (possibly empty — a pure hit cycle moves only the two masks)."""

    __slots__ = ("epoch", "nslots", "grant_mask", "invalid_mask",
                 "response_list", "spec_payload")

    def __init__(self, epoch: int = 0, nslots: int = 0,
                 grant_mask: int = 0, invalid_mask: int = 0,
                 response_list: "ResponseList | None" = None,
                 spec_payload=None):
        self.epoch = epoch
        self.nslots = nslots
        self.grant_mask = grant_mask
        self.invalid_mask = invalid_mask
        self.response_list = response_list if response_list is not None \
            else ResponseList()
        # Fused speculative cycle verdict: the world-reduced fused
        # buffers, [(DataType, buffer), ...] in replay-plan order.
        # None on a classic (two-round) cycle response.
        self.spec_payload = spec_payload

    def __eq__(self, other):
        return (isinstance(other, CacheCycleResponse) and
                all(getattr(self, s) == getattr(other, s)
                    for s in ("epoch", "nslots", "grant_mask",
                              "invalid_mask", "response_list"))
                and _payloads_equal(self.spec_payload,
                                    other.spec_payload))


def _payloads_equal(a, b) -> bool:
    if (a is None) != (b is None):
        return False
    if a is None:
        return True
    return (len(a) == len(b)
            and all(da == db and bytes(ba) == bytes(bb)
                    for (da, ba), (db, bb) in zip(a, b)))


class ResponseList:
    """One cycle's broadcast from the coordinator: ordered, fused responses
    + shutdown bit (reference: message.h:187-214), plus the autotuner's
    currently tuned parameters so workers track the coordinator — the
    wire-level stand-in for the reference's MPI struct param sync
    (reference: parameter_manager.cc:64-78 SyncParams). Zero = untuned.
    """

    __slots__ = ("responses", "shutdown", "tuned_cycle_time_ms",
                 "tuned_fusion_threshold_bytes",
                 "tuned_overlap_buckets")

    def __init__(self, responses: List[Response] | None = None,
                 shutdown: bool = False,
                 tuned_cycle_time_ms: float = 0.0,
                 tuned_fusion_threshold_bytes: int = 0,
                 tuned_overlap_buckets: int = -1):
        self.responses = responses if responses is not None else []
        self.shutdown = shutdown
        self.tuned_cycle_time_ms = tuned_cycle_time_ms
        self.tuned_fusion_threshold_bytes = tuned_fusion_threshold_bytes
        # Autotuned overlap bucket count (-1 = no verdict; 0 = tuned
        # off). Rides next to the fusion/cycle trailer so every rank
        # adopts the coordinator's bucket plan on the same verdict.
        self.tuned_overlap_buckets = tuned_overlap_buckets

    def add_response(self, resp: Response) -> None:
        self.responses.append(resp)

    def __eq__(self, other):
        return (isinstance(other, ResponseList)
                and self.shutdown == other.shutdown
                and self.tuned_cycle_time_ms == other.tuned_cycle_time_ms
                and self.tuned_fusion_threshold_bytes
                    == other.tuned_fusion_threshold_bytes
                and self.tuned_overlap_buckets
                    == other.tuned_overlap_buckets
                and self.responses == other.responses)
