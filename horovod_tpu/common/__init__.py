"""Core runtime shared by every framework adapter.

Mirrors the role of the reference's ``horovod/common`` C++ core
(reference: horovod/common/operations.cc, global_state.h): one
process-global runtime owning a background coordination thread; framework
adapters only differ in how their tensors are staged into it.
"""
