"""Overlap tier: bucketed ready-order dispatch + in-flight steady cycles.

Every perf layer so far made the steady collective *cycle* cheaper
(PR 3 one round-trip, PR 6 zero-copy native, PR 9 wire compression),
but the step stayed strictly sequential: backward finishes, then ONE
blocking fused cycle runs, so wire time adds linearly to compute time.
This module is the scheduling half of the fix (the DDP-bucket /
ByteScheduler lineage — Li et al., VLDB 2020; Peng et al., SOSP 2019):

- :func:`plan_buckets` splits a grouped gradient submission into K
  size-balanced CONTIGUOUS buckets (contiguity preserves ready order —
  gradients become available back-to-front, and a bucket is enqueued
  the moment its members exist). Each bucket negotiates and reduces as
  its own fused speculative / native zero-copy cycle, so early buckets
  ride the wire while the training thread still computes later
  gradients.

- :class:`OverlapRunner` drives up to ``HOROVOD_OVERLAP_INFLIGHT``
  native steady cycles from a dedicated completion thread with the GIL
  released: the background loop *submits* a packed cycle and
  immediately returns to building the next bucket's frame; handles
  complete out of band when the loop drains finished outcomes, so
  ``synchronize()`` only ever blocks on the tail bucket.

Thread-ownership contract (what keeps the response cache coherent):
the runner thread ONLY performs wire I/O (``steady_spec_cycle`` — a
single C call per cycle). Every world-replicated mutation (cache LRU
touches, steady-mask bookkeeping, entry pops and completion callbacks)
happens on the background thread when it drains the runner's outcome
queue, in submission order. Cycles are strictly FIFO on the wire — one
native call at a time — so world-coherent cycle ordering is exactly
the submission order, which is the (world-identical) program order of
the bucketed enqueues. Any deviation outcome stalls the runner; the
background loop resolves it through the classic protocol machinery and
requeues cancelled (never-sent) cycles, so the wire never interleaves.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional

from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck

# Steady predictor slots are capped (runtime keeps the most recent
# masks); more buckets than this could never all stay steady at once.
MAX_BUCKETS = 8


def plan_buckets(nbytes_list: List[int], nbuckets: int,
                 bucket_bytes: int) -> Optional[List[int]]:
    """Split a gradient set into contiguous size-balanced buckets.

    Returns the list of bucket END indices (``[e0, e1, ...]``, each
    exclusive; the last equals ``len(nbytes_list)``), or None when
    bucketing is off or degenerate (fewer than 2 buckets). A pure
    function of per-tensor byte sizes — identical on every rank for
    the same submission, which is what keeps the per-bucket
    negotiation masks world-identical.

    ``nbuckets`` > 0 forces the count; otherwise it derives from
    ``bucket_bytes`` (total / target, DDP's ``bucket_cap_mb`` shape).
    Both 0/unset means off. The count is clamped to [2, MAX_BUCKETS]
    and to the tensor count.
    """
    n = len(nbytes_list)
    total = sum(nbytes_list)
    if n < 2 or total <= 0:
        return None
    if nbuckets <= 0:
        if bucket_bytes <= 0:
            return None
        nbuckets = (total + bucket_bytes - 1) // bucket_bytes
    k = min(int(nbuckets), MAX_BUCKETS, n)
    if k < 2:
        # A submission smaller than one bucket target stays whole —
        # force-splitting it would only multiply protocol rounds.
        return None
    # Greedy boundary walk: close a bucket once its cumulative bytes
    # reach the next j*total/k threshold, keeping every bucket
    # non-empty and leaving at least one tensor per remaining bucket.
    ends: List[int] = []
    acc = 0
    for i, nb in enumerate(nbytes_list):
        acc += nb
        remaining_slots = k - len(ends) - 1
        if remaining_slots <= 0:
            break
        if acc * k >= total * (len(ends) + 1) \
                and (n - (i + 1)) >= remaining_slots:
            ends.append(i + 1)
    ends.append(n)
    return ends if len(ends) >= 2 else None


class InflightCycle:
    """One submitted steady cycle: the packed plan plus everything the
    background loop needs to apply its verdict at drain time."""

    __slots__ = ("plan", "bufs", "bit_requests", "inflight", "seq",
                 "t_submit", "t_start", "t_done", "outcome",
                 "blocked_wait")

    def __init__(self, plan, bufs, bit_requests, inflight, seq: int):
        self.plan = plan
        self.bufs = bufs
        self.bit_requests = bit_requests
        self.inflight = inflight  # [(Response, entries, arrays)]
        self.seq = seq
        self.t_submit = time.monotonic()
        self.t_start = 0.0
        self.t_done = 0.0
        self.outcome = None       # ("done", segs) | ("frame", ...) |
        #                           ("fallback", ...) | ("none", None)
        #                           | ("error", BaseException)
        self.blocked_wait = 0.0   # bg-thread wall time spent waiting


class OverlapRunner:
    """FIFO completion thread for in-flight native steady cycles.

    ``run_fn(plan, bufs)`` is ``controller.steady_spec_cycle`` — wire
    I/O only, GIL released inside the native call. The runner executes
    submitted cycles strictly in order; outcomes park on a completion
    deque the background loop drains. A non-"done" outcome (deviation,
    unsupported probe, transport error) STALLS the runner: no further
    pending cycle is started until the background loop resolves it and
    calls :meth:`cancel_pending` — the wire therefore never carries a
    classic round interleaved with a later speculative frame.
    """

    def __init__(self, run_fn, max_inflight: int, on_complete=None):
        self._run_fn = run_fn
        self._max = max(1, int(max_inflight))
        self._on_complete = on_complete  # e.g. runtime._wake.set
        self._lock = lockdep.lock("overlap.OverlapRunner._lock")
        self._cv = threading.Condition(self._lock)
        self._pending: "collections.deque[InflightCycle]" = \
            collections.deque()
        self._completed: "collections.deque[InflightCycle]" = \
            collections.deque()
        self._active: Optional[InflightCycle] = None
        self._stalled = False
        self._stopped = False
        self._cycles_total = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd-overlap",
                                        daemon=True)
        self._thread.start()

    # -- background-loop API (never called from the runner thread) -----
    @property
    def outstanding(self) -> int:
        """In-flight plus undrained completions — anything whose
        verdict the background loop has not applied yet."""
        with self._lock:
            return (len(self._pending) + len(self._completed)
                    + (1 if self._active else 0))

    @property
    def cycles_total(self) -> int:
        return self._cycles_total

    @property
    def stalled(self) -> bool:
        with self._lock:
            return self._stalled

    def submit(self, cycle: InflightCycle) -> None:
        """Enqueue a cycle; blocks while the in-flight window is full
        or while the same plan is still in flight (its arena views are
        the send buffers on the wire)."""
        with self._cv:
            while not self._stopped and not self._stalled and (
                    len(self._pending) + (1 if self._active else 0)
                    >= self._max
                    or self._plan_busy_locked(cycle.plan)):
                self._cv.wait(0.05)
            if self._stopped or self._stalled:
                # Caller drains/handles the stall; never silently drop.
                raise RuntimeError("overlap runner unavailable")
            self._pending.append(cycle)
            self._cv.notify_all()

    def _plan_busy_locked(self, plan) -> bool:
        if self._active is not None and self._active.plan is plan:
            return True
        return any(c.plan is plan for c in self._pending) \
            or any(c.plan is plan for c in self._completed)

    def pop_completed(self) -> Optional[InflightCycle]:
        with self._cv:
            if not self._completed:
                return None
            c = self._completed.popleft()
            self._cv.notify_all()
            return c

    def wait_completed(self, timeout: float) -> Optional[InflightCycle]:
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._completed:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopped:
                    return None
                self._cv.wait(min(remaining, 0.05))
            c = self._completed.popleft()
            self._cv.notify_all()
            return c

    def cancel_pending(self) -> List[InflightCycle]:
        """Remove every never-started cycle (their frames were NEVER
        sent — safe to requeue) and clear a deviation stall. The
        active cycle, if any, still completes and parks its outcome."""
        with self._cv:
            cancelled = list(self._pending)
            self._pending.clear()
            self._stalled = False
            self._cv.notify_all()
            return cancelled

    def stop(self, timeout: float = 5.0) -> List[InflightCycle]:
        """Teardown: stop accepting work, wake the thread, join, and
        hand back everything undrained (pending + completed) so the
        caller can fail their entries."""
        with self._cv:
            self._stopped = True
            leftovers = list(self._pending)
            self._pending.clear()
            self._cv.notify_all()
        self._thread.join(timeout)
        with self._cv:
            leftovers.extend(self._completed)
            self._completed.clear()
        return leftovers

    # -- runner thread -------------------------------------------------
    def _loop(self) -> None:
        threadcheck.register_role("hvd-overlap")
        while True:
            with self._cv:
                while not self._stopped and (
                        self._stalled or not self._pending):
                    self._cv.wait(0.05)
                if self._stopped:
                    return
                cycle = self._pending.popleft()
                self._active = cycle
                self._cv.notify_all()
            cycle.t_start = time.monotonic()
            try:
                outcome = self._run_fn(cycle.plan, cycle.bufs)
                if outcome is None:
                    cycle.outcome = ("none", None)
                else:
                    cycle.outcome = outcome
            except BaseException as e:  # parked; re-raised at drain
                cycle.outcome = ("error", e)
            cycle.t_done = time.monotonic()
            with self._cv:
                self._active = None
                self._completed.append(cycle)
                self._cycles_total += 1
                if cycle.outcome[0] != "done":
                    # Deviation/error: hold the wire until the
                    # background loop resolves it classically.
                    self._stalled = True
                self._cv.notify_all()
            if self._on_complete is not None:
                try:
                    self._on_complete()
                except Exception:
                    pass
# -- thread-affinity sanitizer (HOROVOD_TPU_THREADCHECK) ------------------
threadcheck.install(OverlapRunner, "_cycles_total",
                    "overlap.OverlapRunner._cycles_total",
                    owner="hvd-overlap")
