"""Rank-0 coordinator: readiness counting, response construction, fusion.

This is the negotiation brain of the framework — the part of the
reference's background loop that turns independently-ordered per-rank
requests into one globally agreed, validated, fused execution order
(reference: horovod/common/operations.cc — ``IncrementTensorCount``
163-189, ``ConstructResponse`` 197-399, the fusion batching loop
1118-1234, ``CheckForStalledTensors`` 543-624).

On TPU this total order matters twice: it preserves Horovod's contract
(any rank may submit in any order) *and* it is exactly the guarantee
multi-controller JAX needs — every process must issue identical XLA
computations in identical order, which the broadcast ResponseList
provides by construction.
"""

from __future__ import annotations

import heapq
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common import lockdep
from horovod_tpu.common import threadcheck
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import wire_dtype as _wd
from horovod_tpu.common.message import (
    DataType, Request, RequestType, Response, ResponseType, datatype_name,
    datatype_size,
)


class _TensorRecord:
    __slots__ = ("requests", "first_request_time")

    def __init__(self):
        self.requests: List[Request] = []
        self.first_request_time = time.monotonic()


class MessageTable:
    """Pending negotiations: tensor name → requests received so far
    (reference: global_state.h:120-125, operations.cc:110-117)."""

    def __init__(self, on_remove=None):
        self._table: Dict[str, _TensorRecord] = {}
        # FIFO of names that became ready this cycle, in readiness order
        # (reference: operations.cc ready_to_reduce, 1069-1079).
        self._ready: List[str] = []
        # Fired with the tensor name whenever a negotiation completes
        # (the StallInspector clears its warned-set entry so a
        # recurring name that stalls AGAIN warns again).
        self._on_remove = on_remove

    def increment_tensor_count(self, msg: Request, size: int,
                               timeline=None) -> bool:
        """Record one rank's request; True when all ``size`` ranks have
        reported (reference: operations.cc:163-189)."""
        name = msg.tensor_name
        rec = self._table.get(name)
        if rec is None:
            rec = _TensorRecord()
            self._table[name] = rec
            if timeline is not None:
                timeline.negotiate_start(name, msg.request_type)
        rec.requests.append(msg)
        if timeline is not None:
            timeline.negotiate_rank_ready(name, msg.request_rank)
        ready = len(rec.requests) == size
        if ready:
            self._ready.append(name)
        return ready

    def pop_ready(self) -> List[str]:
        ready = self._ready
        self._ready = []
        return ready

    def requests_for(self, name: str) -> List[Request]:
        return self._table[name].requests

    def remove(self, name: str) -> None:
        del self._table[name]
        if self._on_remove is not None:
            self._on_remove(name)

    def pending(self) -> List[Tuple[str, float, List[int]]]:
        """(name, age_seconds, ranks_reported) for stall reporting."""
        now = time.monotonic()
        return [(name, now - rec.first_request_time,
                 sorted(r.request_rank for r in rec.requests))
                for name, rec in self._table.items()]

    def __len__(self):
        return len(self._table)


def construct_response(table: MessageTable, name: str,
                       size: int) -> Response:
    """Build the (validated) Response for a fully-negotiated tensor
    (reference: operations.cc:197-399). Removes the entry from the table.

    Validation performed across ranks, any failure → ERROR response that
    every requesting rank surfaces as an exception:
    - mismatched collective op
    - mismatched dtype
    - mismatched shapes (allreduce/broadcast/reducescatter: all dims;
      allgather/alltoall: all dims but dim 0)
    - mismatched root ranks (broadcast)
    - mixed host/device placement
    """
    requests = table.requests_for(name)
    assert len(requests) == size

    error = None

    first = requests[0]
    # Op consistency (reference: operations.cc:223-237).
    for req in requests[1:]:
        if req.request_type != first.request_type:
            error = ("Mismatched collective operations requested: one rank "
                     f"requested {first.request_type.name}, another rank "
                     f"requested {req.request_type.name}.")
            break

    # Dtype consistency (reference: operations.cc:205-221).
    if error is None:
        for req in requests[1:]:
            if req.tensor_type != first.tensor_type:
                error = ("Mismatched data types: one rank sent "
                         f"{datatype_name(first.tensor_type)}, another rank "
                         f"sent {datatype_name(req.tensor_type)}.")
                break

    # Placement consistency (reference: operations.cc:352-365 CPU-vs-GPU).
    if error is None:
        on_device = [req.device >= 0 for req in requests]
        if any(on_device) and not all(on_device):
            error = ("Mismatched tensor placement: some ranks submitted "
                     "host tensors while others submitted device tensors.")

    op = first.request_type
    tensor_sizes: List[int] = []

    if error is None and op in (RequestType.ALLREDUCE,
                                RequestType.BROADCAST,
                                RequestType.REDUCESCATTER,
                                RequestType.ALLTOALL):
        # Exact shape match (reference: operations.cc:240-260).
        for req in requests[1:]:
            if req.tensor_shape != first.tensor_shape:
                error = (f"Mismatched {op.name.lower()} tensor shapes: one "
                         f"rank sent a tensor of shape "
                         f"{list(first.tensor_shape)}, another rank sent a "
                         f"tensor of shape {list(req.tensor_shape)}.")
                break

    if error is None and op == RequestType.ALLGATHER:
        # Same rank; same dims except dim 0 (reference: 262-319).
        for req in requests[1:]:
            if len(req.tensor_shape) != len(first.tensor_shape):
                error = (f"Mismatched {op.name.lower()} tensor ranks: one "
                         f"rank sent a {len(first.tensor_shape)}-d tensor, "
                         f"another rank sent a "
                         f"{len(req.tensor_shape)}-d tensor.")
                break
            if req.tensor_shape[1:] != first.tensor_shape[1:]:
                error = (f"Mismatched {op.name.lower()} tensor shapes: "
                         "dimensions beyond the first must match on every "
                         f"rank; got {list(first.tensor_shape)} and "
                         f"{list(req.tensor_shape)}.")
                break
        if error is None:
            if not first.tensor_shape:
                error = (f"Rank zero tensors cannot be "
                         f"{op.name.lower()}ed: at least one dimension is "
                         "required.")
            else:
                # dim-0 size per rank, in rank order (reference: 300-316).
                by_rank = sorted(requests, key=lambda r: r.request_rank)
                tensor_sizes = [r.tensor_shape[0] for r in by_rank]

    if error is None and op == RequestType.ALLTOALL:
        if not first.tensor_shape or first.tensor_shape[0] % size != 0:
            error = ("alltoall requires the first dimension to be "
                     f"divisible by the world size {size}; got shape "
                     f"{list(first.tensor_shape)}.")

    if error is None and op == RequestType.REDUCESCATTER:
        if not first.tensor_shape or first.tensor_shape[0] % size != 0:
            error = ("reducescatter requires the first dimension to be "
                     f"divisible by the world size {size}; got shape "
                     f"{list(first.tensor_shape)}.")

    if error is None and op == RequestType.BROADCAST:
        # Root rank consistency (reference: operations.cc:321-337).
        for req in requests[1:]:
            if req.root_rank != first.root_rank:
                error = ("Mismatched broadcast root ranks: one rank "
                         f"specified root rank {first.root_rank}, another "
                         f"rank specified root rank {req.root_rank}.")
                break
        if error is None and not (0 <= first.root_rank < size):
            error = (f"Invalid broadcast root rank {first.root_rank} for "
                     f"world size {size}.")

    devices = [0] * size
    for req in requests:
        devices[req.request_rank] = req.device

    table.remove(name)

    if error is not None:
        return Response(response_type=ResponseType.ERROR,
                        tensor_names=[name], error_message=error)

    if op == RequestType.ALLREDUCE:
        numel = 1
        for d in first.tensor_shape:
            numel *= d
        # Wire-dtype negotiation (common/wire_dtype.py): the verdict is
        # the LEAST aggressive proposal across ranks — a heterogeneous-
        # KNOB world (one rank launched with compression off) degrades
        # to a dtype everyone speaks rather than erroring, mirroring
        # how the fusion threshold heals. (This heals knob divergence
        # only, not build divergence: the control frames themselves
        # carry the proposal byte, so every rank must run the same
        # wire layout.) Only compressible dtypes (f32/f64) ever carry
        # a nonzero verdict.
        wire = _wd.resolve(req.wire_dtype for req in requests) \
            if first.tensor_type in _wd.COMPRESSIBLE else _wd.WIRE_NONE
        return Response(response_type=ResponseType.ALLREDUCE,
                        tensor_names=[name], devices=devices,
                        tensor_sizes=[numel],
                        prescale_factor=first.prescale_factor,
                        postscale_factor=first.postscale_factor,
                        wire_dtype=wire)
    if op == RequestType.ALLGATHER:
        # Same min-resolve as the allreduce branch, then the int8 ->
        # bf16 degrade: gathered blocks concatenate into ONE payload,
        # which cannot carry per-rank int8 scale headers (see
        # wire_dtype.allgather_wire).
        wire = _wd.allgather_wire(
            _wd.resolve(req.wire_dtype for req in requests)) \
            if first.tensor_type in _wd.COMPRESSIBLE else _wd.WIRE_NONE
        return Response(response_type=ResponseType.ALLGATHER,
                        tensor_names=[name], devices=devices,
                        tensor_sizes=tensor_sizes,
                        wire_dtype=wire)
    if op == RequestType.BROADCAST:
        return Response(response_type=ResponseType.BROADCAST,
                        tensor_names=[name], devices=devices)
    if op == RequestType.ALLTOALL:
        return Response(response_type=ResponseType.ALLTOALL,
                        tensor_names=[name], devices=devices)
    if op == RequestType.REDUCESCATTER:
        numel = 1
        for d in first.tensor_shape:
            numel *= d
        # Full negotiation including int8: the star leg dequantizes
        # per-rank contributions into a full-precision accumulator and
        # requantizes per OUTPUT slice, so per-rank scales never mix
        # (ops/socket_ops.py). Ring routing degrades via ring_wire at
        # the backend, exactly like allreduce.
        wire = _wd.resolve(req.wire_dtype for req in requests) \
            if first.tensor_type in _wd.COMPRESSIBLE else _wd.WIRE_NONE
        return Response(response_type=ResponseType.REDUCESCATTER,
                        tensor_names=[name], devices=devices,
                        tensor_sizes=[numel],
                        wire_dtype=wire)
    if op == RequestType.BARRIER:
        return Response(response_type=ResponseType.BARRIER,
                        tensor_names=[name])
    # JOIN (elastic membership) is wire-defined for forward compat but
    # not implemented; answer with ERROR rather than killing the loop.
    return Response(response_type=ResponseType.ERROR, tensor_names=[name],
                    error_message=f"Operation {op.name} is not supported "
                    "by this coordinator.")


def _response_bytes(resp: Response, dtype: DataType,
                    slice_numels: Dict[str, int]) -> int:
    """Payload bytes a response moves. ALLREDUCE tensor_sizes are
    per-tensor numels; ALLGATHER tensor_sizes are per-rank dim-0 rows,
    so the output size is rows × slice-numel (the reference's
    ``TotalByteSizeOfAllgatherOutput``, operations.cc:1178-1191)."""
    if resp.response_type == ResponseType.ALLGATHER:
        return (sum(resp.tensor_sizes)
                * slice_numels[resp.tensor_names[0]]
                * datatype_size(dtype))
    return sum(resp.tensor_sizes) * datatype_size(dtype)


def fuse_responses(responses: List[Response],
                   dtypes: Dict[str, DataType],
                   fusion_threshold_bytes: int,
                   slice_numels: Dict[str, int] = None) -> List[Response]:
    """Batch compatible consecutive ALLREDUCE **and ALLGATHER**
    responses under the fusion threshold, with the reference's
    look-ahead-skip behaviour: a tensor that cannot join the current
    batch does not end it — later compatible tensors may still join,
    and skipped ones are retried in order
    (reference: horovod/common/operations.cc:1118-1234; the allgather
    branch 1172-1234 accounts bytes as dim0-sum × slice-size).

    ``dtypes`` maps tensor name → dtype (fusion requires same dtype and
    same device placement; we fuse host-side entries and device entries
    separately via the devices signature). ``slice_numels`` maps
    name → elements per dim-0 row, needed for allgather byte
    accounting. A fused ALLGATHER response keeps ``tensor_sizes``
    entry-major: sizes[ec * world_size + rc] is entry ec's dim-0
    contribution from rank rc (reference:
    Response::add_allgather_response, message.cc:306-314).
    """
    # Without slice numels, allgather byte accounting is impossible —
    # pass allgathers through unfused (pre-fusion behavior) instead of
    # guessing sizes or crashing the coordinator loop.
    fusable = ((ResponseType.ALLREDUCE, ResponseType.ALLGATHER)
               if slice_numels is not None
               else (ResponseType.ALLREDUCE,))
    slice_numels = slice_numels or {}
    # Deques keep every enqueue/dequeue O(1): the previous list.pop(0)
    # version shifted the whole remainder on each pop, which made even
    # the no-fusion pass O(n^2) — invisible at 8 tensors/cycle, real
    # money in a 64-rank many-tensor storm (guarded by
    # tests/test_coordinator.py::test_coordinator_cycle_cost_64_ranks).
    queue = deque(responses)
    fused: List[Response] = []
    while queue:
        resp = queue.popleft()
        if resp.response_type not in fusable:
            fused.append(resp)
            continue
        dtype = dtypes[resp.tensor_names[0]]
        tensor_bytes = _response_bytes(resp, dtype, slice_numels)
        if tensor_bytes >= fusion_threshold_bytes:
            fused.append(resp)
            continue
        skipped: deque = deque()
        while queue:
            cand = queue.popleft()
            joinable = (
                cand.response_type == resp.response_type
                and dtypes[cand.tensor_names[0]] == dtype
                and cand.devices == resp.devices
                and cand.prescale_factor == resp.prescale_factor
                and cand.postscale_factor == resp.postscale_factor
                # one fused buffer = one wire representation and one
                # data-plane route; mixed verdicts must not share it
                and cand.wire_dtype == resp.wire_dtype
                and cand.algorithm == resp.algorithm)
            if joinable:
                # Byte accounting once per candidate, after the cheap
                # compatibility checks pass (and only then — computing
                # it first would price every incompatible candidate
                # too, and the allgather branch does real arithmetic).
                cand_bytes = _response_bytes(cand, dtype, slice_numels)
                joinable = (tensor_bytes + cand_bytes
                            <= fusion_threshold_bytes)
            if joinable:
                for n in cand.tensor_names:
                    resp.add_tensor_name(n)
                for s in cand.tensor_sizes:
                    resp.add_tensor_size(s)
                tensor_bytes += cand_bytes
            else:
                skipped.append(cand)
        queue = skipped
        fused.append(resp)
    return fused


# Response types whose negotiated verdicts are worth replaying: the
# signature (op, dtype, shape, root, device, scales) fully determines
# the Response, so a steady-state training loop resubmitting the same
# tensors can skip ConstructResponse entirely. BARRIER is pure
# negotiation (nothing to replay) and JOIN/ERROR are one-shot.
CACHEABLE_REQUESTS = frozenset((
    RequestType.ALLREDUCE, RequestType.ALLGATHER, RequestType.BROADCAST,
    RequestType.ALLTOALL, RequestType.REDUCESCATTER,
))
CACHEABLE_RESPONSES = frozenset((
    ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
    ResponseType.BROADCAST, ResponseType.ALLTOALL,
    ResponseType.REDUCESCATTER,
))


def iter_set_bits(mask: int):
    """Set bit positions of ``mask``, ascending — THE canonical order
    every mask-driven cache mutation and replay uses. One shared
    implementation on purpose: eviction, LRU touch, and replay must
    iterate bit-identically on every rank or the caches diverge."""
    while mask:
        low = mask & -mask
        mask ^= low
        yield low.bit_length() - 1


class _CacheEntry:
    __slots__ = ("name", "signature", "response", "dtype", "slice_numel",
                 "slot")

    def __init__(self, name: str, signature: tuple, response: Response,
                 dtype: DataType, slice_numel: int, slot: int):
        self.name = name
        self.signature = signature
        self.response = response
        self.dtype = dtype
        self.slice_numel = slice_numel
        self.slot = slot

    def clone_response(self) -> Response:
        """Fresh Response for fusion: fuse_responses mutates the batch
        head's name/size lists, which must never reach the cached copy."""
        r = self.response
        return Response(response_type=r.response_type,
                        tensor_names=list(r.tensor_names),
                        error_message=r.error_message,
                        devices=list(r.devices),
                        tensor_sizes=list(r.tensor_sizes),
                        prescale_factor=r.prescale_factor,
                        postscale_factor=r.postscale_factor,
                        wire_dtype=r.wire_dtype,
                        algorithm=r.algorithm)


class ResponseCache:
    """World-coherent LRU cache of negotiated per-tensor Responses —
    the steady-state negotiation fast path (upstream analog: the
    bit-vector response cache behind ``HOROVOD_CACHE_CAPACITY``, the
    coordinator-scalability fix that followed the original design;
    conceptually the same move as PyTorch DDP's pre-built gradient
    buckets).

    Coherence contract: every structural mutation (put, eviction,
    LRU touch) is driven ONLY by world-identical inputs — the broadcast
    response stream for puts, the coordinator's broadcast grant and
    invalidate masks for touches/evictions — applied in one canonical
    order (ascending slot order for mask-driven events, stream order
    for puts). Signatures are rank-LOCAL (an allgather's dim-0 and the
    device id differ per rank); everything else (slot assignment, LRU
    order, eviction choice, epoch) is bit-identical across the world,
    which is what lets a rank's slot bit stand in for its serialized
    Request. ``epoch`` counts structural events and rides every
    bitmask frame so real divergence fails fast instead of silently
    executing mismatched collectives."""

    MISS, HIT, INVALID = range(3)

    def __init__(self, capacity: int, epoch0: int = 0):
        """``epoch0`` seeds the epoch counter. Elastic worlds
        (common/elastic.py) seed it from the world GENERATION so a
        control frame surviving from a pre-resize world mismatches
        every epoch equality gate and fails fast, instead of silently
        negotiating against a rebuilt cache that happens to share
        epoch numbers with the old one."""
        if capacity <= 0:
            raise ValueError("ResponseCache capacity must be positive")
        self.capacity = capacity
        # The cache is confined to the coordinator's cycle thread;
        # state_fingerprint is a test probe called on quiesced worlds.
        # hvdlint: owned-by=hvd-background -- cycle-thread-confined cache
        self.epoch = epoch0  # hvdlint: world-replicated
        # name -> entry, maintained in LRU order (first = oldest)
        # hvdlint: owned-by=hvd-background -- cycle-thread-confined cache
        self._lru: "OrderedDict[str, _CacheEntry]" = \
            OrderedDict()  # hvdlint: world-replicated
        # hvdlint: owned-by=hvd-background -- cycle-thread-confined cache
        self._slots: List[Optional[_CacheEntry]] = \
            []  # hvdlint: world-replicated
        # min-heap of freed slot indices
        self._free: List[int] = []  # hvdlint: world-replicated
        # local observability (not part of the coherent state)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def nslots(self) -> int:
        return len(self._slots)

    @staticmethod
    def signature(req: Request) -> tuple:
        """Everything that determines a Request's negotiated verdict
        (rank-local: shape and device legitimately differ per rank).
        The proposed wire dtype is part of it: a knob change must
        renegotiate the compression verdict, not replay a stale one."""
        return (int(req.request_type), int(req.tensor_type),
                req.tensor_shape, req.root_rank, req.device,
                req.prescale_factor, req.postscale_factor,
                req.wire_dtype)

    def lookup(self, req: Request) -> Tuple[int, int]:
        """(state, slot): HIT — the queued request matches the cached
        signature bit-for-bit; INVALID — same name, different signature
        (shape/dtype/... changed: the slot must be evicted world-wide);
        MISS — not cached. Never mutates LRU order (a local lookup is
        not a world-identical event)."""
        e = self._lru.get(req.tensor_name)
        if e is None:
            self.misses += 1
            return self.MISS, -1
        # Field-wise compare against the stored signature rather than
        # building a fresh signature tuple per lookup — this runs once
        # per queued request per cycle, the steady state's hottest
        # rank-local loop. Indices mirror signature().
        s = e.signature
        if (s[0] == req.request_type and s[1] == req.tensor_type
                and s[2] == req.tensor_shape and s[3] == req.root_rank
                and s[4] == req.device
                and s[5] == req.prescale_factor
                and s[6] == req.postscale_factor
                and s[7] == req.wire_dtype):
            self.hits += 1
            return self.HIT, e.slot
        self.misses += 1
        return self.INVALID, e.slot

    def put(self, name: str, signature: tuple, response: Response,
            dtype: DataType, slice_numel: int) -> None:
        """Insert/refresh from the negotiated response stream. Callers
        MUST invoke this in broadcast-stream order on every rank — the
        LRU order and capacity evictions derive from the call order."""
        e = self._lru.get(name)
        if e is not None:
            e.signature = signature
            e.response = response
            e.dtype = dtype
            e.slice_numel = slice_numel
            self._lru.move_to_end(name)
            self.epoch += 1
            return
        if len(self._lru) >= self.capacity:
            _, victim = self._lru.popitem(last=False)
            self._slots[victim.slot] = None
            heapq.heappush(self._free, victim.slot)
            self.epoch += 1
            self.evictions += 1
        if self._free:
            slot = heapq.heappop(self._free)
        else:
            slot = len(self._slots)
            self._slots.append(None)
        entry = _CacheEntry(name, signature, response, dtype,
                            slice_numel, slot)
        self._slots[slot] = entry
        self._lru[name] = entry
        self.epoch += 1

    def evict_slots(self, mask: int) -> None:
        """Evict every slot set in ``mask`` (the coordinator's OR'ed
        invalidate mask), ascending slot order."""
        for slot in iter_set_bits(mask):
            self._evict(slot)

    def evict_name(self, name: str) -> None:
        e = self._lru.get(name)
        if e is not None:
            self._evict(e.slot)

    def _evict(self, slot: int) -> None:
        e = self._slots[slot]
        if e is None:
            return
        self._slots[slot] = None
        del self._lru[e.name]
        heapq.heappush(self._free, slot)
        self.epoch += 1
        self.evictions += 1

    def touch_mask(self, mask: int) -> None:
        """Mark granted slots most-recently-used, ascending slot order
        (grants are world-identical, so LRU order stays coherent).
        Does not bump the epoch: no slot<->name binding changes, and
        steady-state replay plans stay valid across hit cycles."""
        for slot in iter_set_bits(mask):
            e = self._slots[slot]
            if e is not None:
                self._lru.move_to_end(e.name)

    def slot_mask(self, response_type: ResponseType) -> int:
        """Mask of occupied slots holding a verdict of
        ``response_type`` — read-only (the coordinator's wire-plan
        eviction builds its broadcast invalid mask from it)."""
        mask = 0
        for e in self._slots:
            if e is not None \
                    and e.response.response_type == response_type:
                mask |= 1 << e.slot
        return mask

    def entry(self, slot: int) -> _CacheEntry:
        e = self._slots[slot]
        if e is None:
            raise KeyError(f"response cache slot {slot} is empty")
        return e

    def state_fingerprint(self) -> tuple:
        """(epoch, ((slot, name) ascending), LRU name order) — the
        coherent (rank-invariant) part of the state, for tests that
        assert two ranks' caches marched in lockstep."""
        return (self.epoch,
                tuple((e.slot, e.name) for e in self._slots
                      if e is not None),
                tuple(self._lru))


class StallInspector:
    """Coordinator-side stall detection
    (reference: operations.cc:543-624 CheckForStalledTensors; env knobs
    HOROVOD_STALL_CHECK_TIME_SECONDS / HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)."""

    def __init__(self, size: int, warning_time: float = 60.0,
                 shutdown_time: float = 0.0, disabled: bool = False):
        self.size = size
        self.warning_time = warning_time
        self.shutdown_time = shutdown_time
        self.disabled = disabled
        self._last_check = time.monotonic()
        # Warned-set is touched from two threads: the coordinator's
        # cycle thread warns (check), while tensor_completed fires
        # from whichever thread removes the entry (the MessageTable
        # on_remove hook — the submitting thread on the enqueue-fail
        # path). The membership test and the add must be atomic
        # against the discard or a name warns twice.
        self._warned_lock = lockdep.lock(
            "coordinator.StallInspector._warned_lock")
        self._warned: set = set()

    def should_check(self) -> bool:
        if self.disabled or self.warning_time <= 0:
            return False
        return time.monotonic() - self._last_check >= self.warning_time

    def tensor_completed(self, name: str) -> None:
        """A stalled tensor finally negotiated: forget that we warned
        about it, so the SAME recurring name stalling again later in
        the process lifetime warns again (MessageTable.remove hook)."""
        with self._warned_lock:
            self._warned.discard(name)

    def check(self, table: MessageTable, cache_stats: str = "",
              world_stats: str = "",
              straggler_stats: str = "") -> bool:
        """Log a report of stalled tensors; returns True if the shutdown
        threshold was exceeded (caller must initiate shutdown).
        ``cache_stats`` — a one-line negotiation-cache summary (hits /
        misses / cached cycles) surfaced with the periodic report so a
        timeline reader can tell whether negotiation time went to full
        rounds or to the bitmask fast path. ``world_stats`` — steady-
        state health context (world cycle, tensor-queue depth,
        per-peer heartbeat ages labeled in the coordinator clock,
        per-peer clock offsets, timeline drop count) appended to each
        stall warning so one warning carries enough to diagnose
        without a second tool. ``straggler_stats`` — the per-cycle
        critical-path attribution line from the trace plane's arrival
        stamps ("rank 3 last-arriver in 84% of the last 1000
        gathers"), its own report line so the slow RANK is named even
        when nothing is stalled outright."""
        self._last_check = time.monotonic()
        if cache_stats:
            hlog.info(f"negotiation {cache_stats}")
        if world_stats:
            hlog.info(f"world health: {world_stats}")
        if straggler_stats:
            hlog.info(f"stragglers: {straggler_stats}")
        suffix = f" [world: {world_stats}]" if world_stats else ""
        must_shutdown = False
        for name, age, ranks_reported in table.pending():
            if age < self.warning_time:
                continue
            missing = [r for r in range(self.size)
                       if r not in ranks_reported]
            with self._warned_lock:
                if name in self._warned:
                    if self.shutdown_time > 0 and \
                            age >= self.shutdown_time:
                        must_shutdown = True
                    continue
                self._warned.add(name)
            hlog.warning(
                f"One or more tensors were submitted to be reduced, "
                f"gathered or broadcasted by subset of ranks and are "
                f"waiting for remainder of ranks for more than "
                f"{int(age)} seconds. Stalled op: {name} "
                f"[ready ranks: {ranks_reported}, "
                f"waiting on ranks: {missing}]{suffix}")
            if self.shutdown_time > 0 and age >= self.shutdown_time:
                hlog.error(
                    f"Stalled tensor {name} exceeded the shutdown "
                    f"threshold of {self.shutdown_time} s; shutting down.")
                must_shutdown = True
        return must_shutdown
# -- thread-affinity sanitizer (HOROVOD_TPU_THREADCHECK) ------------------
# Both fields are cycle-thread-confined after construction; the first
# write (the constructor, on whatever thread builds the coordinator)
# is free by Thread.start()'s happens-before.
threadcheck.install(ResponseCache, "epoch",
                    "coordinator.ResponseCache.epoch",
                    owner="hvd-background")
threadcheck.install(StallInspector, "_last_check",
                    "coordinator.StallInspector._last_check",
                    owner="hvd-background")
