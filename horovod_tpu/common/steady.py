"""Native steady-cycle plans: the zero-copy data plane for the fused
speculative cycle.

PR 3 collapsed a steady training step into ONE world round-trip, but
every byte still flowed through Python: pack into a fresh buffer,
serialize into a bytes object, recv into a bytearray, copy again for
writability. This module precomputes everything that is CONSTANT
across steady steps — the CACHED_SPEC frame's prefix and per-segment
headers (from wire.spec_frame_parts, so native and pure-Python ranks
share one byte layout), the fusion-arena segment views the packed
tensors land in, and the ctypes pointer bundles the native core
consumes — so a steady step becomes: one native pack into the arena,
one ``hvd_steady_worker``/``hvd_steady_coord`` call (GIL released)
that sends, reduces and receives straight between sockets and numpy
memory, and one unpack into fresh per-entry outputs. No intermediate
bytes object is materialized anywhere on the path
(``hvd_data_copies_total`` counts the fallback copies that remain).

Role split: a plan is world-replicated LAYOUT (derived from the
granted mask — identical on every rank); per-step tensor data flows
through :meth:`SteadyPlan.pack`. Receive destinations are always
fresh per-step arrays — never arena memory — so user-visible outputs
can never be clobbered by a later step (see common/arena.py).
"""

from __future__ import annotations

import ctypes
import errno
from typing import Dict, List, Tuple

import numpy as np

from horovod_tpu import native as _native
from horovod_tpu.common import wire
from horovod_tpu.common.arena import FusionArena, concat_into

_u8p = ctypes.POINTER(ctypes.c_uint8)

# Outcome kinds shared with the controllers.
DONE = "done"       # cycle completed natively; payload = result segments
FRAME = "frame"     # worker deviation: (tag, payload bytes)
DEV = "dev"         # coordinator deviation: (peer_idx, tag, payload)
ERR = "err"         # transport failure: negative errno


class SteadyPlan:
    """Precomputed layout of one steady fused cycle (one grant mask at
    one cache epoch under one fusion threshold)."""

    __slots__ = ("epoch", "nslots", "mask", "seg_dtypes",
                 "seg_np_dtypes", "seg_nbytes", "seg_counts",
                 "seg_codes", "seg_src_dtypes", "prefix", "seg_hdrs",
                 "payload_nbytes", "arena", "send_views",
                 "stage_views", "native_ok", "cache", "chunk_bytes",
                 "chunked")

    def __init__(self, epoch: int, nslots: int, mask: int,
                 segments, arena: FusionArena, chunk_bytes: int = 0,
                 world_id: int = 0):
        """``segments``: [(DataType, np_dtype, nbytes, src_np_dtype),
        ...] in replay-plan order, where ``np_dtype``/``nbytes``
        describe the ON-WIRE representation and ``src_np_dtype`` names
        the tensors' real dtype when a negotiated wire dtype
        compresses this segment (None = uncompressed; a legacy
        3-tuple means the same).

        ``chunk_bytes`` > 0 arms chunked pipelined transfer on the
        worker half (HOROVOD_OVERLAP_CHUNK_BYTES): pack leaves
        compressed segments in their full-precision staging views and
        ``hvd_steady_worker_chunked`` casts them chunk-by-chunk
        interleaved with the send — compression of chunk i+1 overlaps
        the kernel-buffered transmission of chunk i. Wire bytes are
        identical either way."""
        self.epoch = epoch
        self.nslots = nslots
        self.mask = mask
        segments = [tuple(s) + (None,) if len(s) == 3 else tuple(s)
                    for s in segments]
        self.seg_dtypes = [s[0] for s in segments]
        self.seg_np_dtypes = [np.dtype(s[1]) for s in segments]
        self.seg_nbytes = [s[2] for s in segments]
        self.seg_src_dtypes = [None if s[3] is None else np.dtype(s[3])
                               for s in segments]
        self.seg_counts = [n // npdt.itemsize
                           for npdt, n in zip(self.seg_np_dtypes,
                                              self.seg_nbytes)]
        codes = [_native._DTYPE_CODES.get(str(npdt))
                 for npdt in self.seg_np_dtypes]
        self.seg_codes = codes
        # The native coordinator must be able to reduce every segment
        # in C; one exotic dtype degrades the whole cycle to Python.
        self.native_ok = bool(segments) and all(c is not None
                                                for c in codes)
        # Tenant worlds lead the constant prefix with the world-id
        # envelope (wire.stamp_world) so the native byte-compare and
        # the classically-serialized frame stay byte-identical.
        self.prefix, self.seg_hdrs = wire.spec_frame_parts(
            epoch, nslots, mask,
            [(dt, n) for dt, n in zip(self.seg_dtypes,
                                      self.seg_nbytes)],
            world_id=world_id)
        self.payload_nbytes = (len(self.prefix)
                               + sum(len(h) for h in self.seg_hdrs)
                               + sum(self.seg_nbytes))
        self.arena = arena
        # Send-side segment views: stable arena memory, so the iovec
        # pointers below survive across steps. Compressed segments
        # additionally get a full-precision STAGING view right after
        # the wire region — pack concatenates + prescales there, then
        # casts once into the wire view (send bytes only ever live in
        # the arena; the staging bytes never reach the wire).
        off = 0
        views = []
        wire_total = sum(self.seg_nbytes)
        stage_total = sum(
            cnt * src.itemsize
            for cnt, src in zip(self.seg_counts, self.seg_src_dtypes)
            if src is not None)
        arena.ensure(wire_total + stage_total)
        for npdt, n, count in zip(self.seg_np_dtypes, self.seg_nbytes,
                                  self.seg_counts):
            views.append(arena.typed(off, npdt, count))
            off += n
        self.send_views = views
        stages = []
        soff = wire_total
        for count, src in zip(self.seg_counts, self.seg_src_dtypes):
            if src is None:
                stages.append(None)
            else:
                stages.append(arena.typed(soff, src, count))
                soff += count * src.itemsize
        self.stage_views = stages
        # Chunked pipelined transfer engages only when a segment
        # actually compresses (src dtype present), the knob is armed,
        # and the native library exports the chunked entry point —
        # every other combination keeps the classic one-shot send.
        self.chunk_bytes = int(chunk_bytes)
        self.chunked = False
        if self.chunk_bytes > 0 and any(
                s is not None for s in self.seg_src_dtypes):
            lib = _native.get()

            def _castable(src, wire_code):
                # hvd_cast only speaks f32 <-> bf16/f16 (codes 0 <->
                # 6/5); any other pair (e.g. float64 sources) must
                # keep the Python cast + classic one-shot send, or
                # the chunk loop would -EINVAL mid-frame and abort a
                # healthy world.
                if src is None:
                    return True
                return (_native._DTYPE_CODES.get(str(src)) == 0
                        and wire_code in (5, 6))

            self.chunked = (lib is not None
                            and hasattr(lib, "hvd_steady_worker_chunked")
                            and all(
                                _castable(s, c) for s, c in
                                zip(self.seg_src_dtypes,
                                    self.seg_codes)))
        # Role-specific ctypes bundles attached by the controllers;
        # dies with the plan (plans are epoch-memoized in the runtime).
        self.cache: Dict = {}

    @property
    def nseg(self) -> int:
        return len(self.seg_nbytes)

    # -- per-step packing ------------------------------------------------
    def pack(self, seg_arrays: List[List[np.ndarray]],
             prescales: List[float],
             use_arena: bool = True) -> List[np.ndarray]:
        """Pack each segment's entry tensors into contiguous send
        buffers: the persistent arena views (workers — stable iovec
        pointers, zero allocations) or fresh accumulators
        (coordinator — its outputs alias the reduced buffers, which
        must therefore never be arena memory)."""
        from horovod_tpu.common import wire_dtype as _wd
        bufs = []
        for j, arrays in enumerate(seg_arrays):
            npdt = self.seg_np_dtypes[j]
            src_dt = self.seg_src_dtypes[j]
            flats = [a.reshape(-1) if a.flags["C_CONTIGUOUS"]
                     else np.ascontiguousarray(a).reshape(-1)
                     for a in arrays]
            if src_dt is None:
                dst = self.send_views[j] if use_arena \
                    else np.empty(self.seg_counts[j], npdt)
                concat_into(flats, dst)
                f = prescales[j]
                if f != 1.0:
                    np.multiply(dst, np.asarray(f, npdt), out=dst)
                bufs.append(dst)
                continue
            # Compressed segment: concat + prescale in the tensors'
            # real dtype (staging), one cast into the wire view — the
            # native hvd_cast kernel when it speaks the pair. With the
            # chunked worker armed the cast is DEFERRED: the native
            # send loop casts chunk-by-chunk interleaved with the
            # wire (frame_bytes materializes it for fallback paths).
            stage = self.stage_views[j] if use_arena \
                else np.empty(self.seg_counts[j], src_dt)
            concat_into(flats, stage)
            f = prescales[j]
            if f != 1.0:
                np.multiply(stage, np.asarray(f, src_dt), out=stage)
            dst = self.send_views[j] if use_arena \
                else np.empty(self.seg_counts[j], npdt)
            if not (self.chunked and use_arena):
                _wd.cast_into(stage, dst)
            bufs.append(dst)
        return bufs

    def adopt_packed(self, bufs: List[np.ndarray]):
        """Adopt per-segment send buffers packed OUTSIDE this plan —
        the ICI plane's fused-psum executable emits the bucket already
        concatenated, prescaled and cast to the wire dtype (ops/
        xla_ops.py IciPlane.fused_pack). Validates that each buffer is
        byte-compatible with the segment the wire header declares and
        returns the list ready for the steady cycle; None on any
        mismatch so the caller re-packs on the host path instead of
        shipping a malformed frame. Foreign buffers deliberately do
        NOT alias the arena views: run_worker_cycle rebuilds its send
        pointers for them and skips the deferred chunked cast (the
        payload is already in wire form)."""
        if len(bufs) != self.nseg:
            return None
        out = []
        for j, b in enumerate(bufs):
            if b is None or not isinstance(b, np.ndarray):
                return None
            if b.dtype != self.seg_np_dtypes[j] \
                    or b.nbytes != self.seg_nbytes[j]:
                return None
            if not b.flags["C_CONTIGUOUS"]:
                b = np.ascontiguousarray(b)
            out.append(b)
        return out

    def materialize_wire(self) -> None:
        """Deferred-cast fallback: fill the wire views from staging —
        exactly the bytes the chunked native send would have produced
        (one cast pass; chunking never changes wire bytes)."""
        from horovod_tpu.common import wire_dtype as _wd
        for j, src in enumerate(self.seg_src_dtypes):
            if src is not None:
                _wd.cast_into(self.stage_views[j], self.send_views[j])

    def frame_bytes(self, bufs: List[np.ndarray]) -> bytes:
        """Serialize a full CACHED_SPEC frame from packed buffers —
        byte-identical to wire.serialize_cycle_request. Fallback paths
        only (the native path never materializes the frame)."""
        if self.chunked and any(b is v for b, v in
                                zip(bufs, self.send_views)):
            self.materialize_wire()
        parts = [self.prefix]
        for h, b in zip(self.seg_hdrs, bufs):
            parts.append(h)
            parts.append(memoryview(b.view(np.uint8)))
        return b"".join(parts)

    def result_segments(self, raw: np.ndarray):
        """[(DataType, typed view)] over a contiguous result buffer
        holding the concatenated segment data."""
        out = []
        off = 0
        for dt, npdt, n, count in zip(self.seg_dtypes,
                                      self.seg_np_dtypes,
                                      self.seg_nbytes,
                                      self.seg_counts):
            out.append((dt, raw[off:off + n].view(npdt)))
            off += n
        return out


def _mkbuf(b: bytes):
    return (ctypes.c_uint8 * max(1, len(b))).from_buffer_copy(
        b or b"\x00")


def _c_common(plan: SteadyPlan) -> Dict:
    """ctypes pieces both roles share, cached on the plan."""
    c = plan.cache.get("common")
    if c is None:
        hdr_bufs = [_mkbuf(h) for h in plan.seg_hdrs]
        c = {
            "prefix": _mkbuf(plan.prefix),
            "hdr_bufs": hdr_bufs,  # keep alive behind the pointers
            "hdr_ptrs": (_u8p * plan.nseg)(
                *[ctypes.cast(b, _u8p) for b in hdr_bufs]),
            "hdr_lens": (ctypes.c_int64 * plan.nseg)(
                *[len(h) for h in plan.seg_hdrs]),
            "seg_lens": (ctypes.c_int64 * plan.nseg)(*plan.seg_nbytes),
            "seg_codes": (ctypes.c_int * plan.nseg)(*plan.seg_codes),
        }
        plan.cache["common"] = c
    return c


def _hb_ms(hb) -> Tuple[int, int]:
    """Channel.arm's (timeout_s, interval_s, on_idle) -> native
    (timeout_ms, interval_ms); (-1, -1) blocks forever."""
    if hb is None:
        return -1, -1
    timeout_s, interval_s = hb[0], hb[1]
    return max(1, int(timeout_s * 1000)), max(1, int(interval_s * 1000))


def run_worker_cycle(lib, plan: SteadyPlan, fd: int, secret: bytes,
                     bufs: List[np.ndarray], skip_tags: bytes,
                     req_tag: int, resp_tag: int, hb):
    """One native steady cycle, worker side. Returns
    (DONE, result_segments) | (FRAME, tag, payload) | (ERR, rc)."""
    c = _c_common(plan)
    b = plan.cache.get("worker")
    if b is None:
        b = {
            "secret": _mkbuf(secret),
            "skip": _mkbuf(skip_tags),
            "nskip": len(skip_tags),
            # Arena views are stable: the send iovec never rebuilds.
            "send_ptrs": (ctypes.c_void_p * plan.nseg)(
                *[v.ctypes.data for v in plan.send_views]),
        }
        plan.cache["worker"] = b
    if bufs is not plan.send_views and \
            any(x is not y for x, y in zip(bufs, plan.send_views)):
        # Defensive: a caller that packed elsewhere still works.
        send_ptrs = (ctypes.c_void_p * plan.nseg)(
            *[v.ctypes.data for v in bufs])
    else:
        send_ptrs = b["send_ptrs"]
    result = np.empty(sum(plan.seg_nbytes), np.uint8)
    recv_ptrs = (ctypes.c_void_p * plan.nseg)()
    off = 0
    for j, n in enumerate(plan.seg_nbytes):
        recv_ptrs[j] = result[off:off + n].ctypes.data
        off += n
    timeout_ms, interval_ms = _hb_ms(hb)
    dev_buf = _u8p()
    dev_len = ctypes.c_int64()
    dev_tag = ctypes.c_uint8()
    if plan.chunked and send_ptrs is b["send_ptrs"]:
        # Chunked pipelined send: staging holds the full-precision
        # bytes; the C loop casts wire chunks interleaved with the
        # send (one fused cast+HMAC pass when frame auth is armed).
        ch = plan.cache.get("chunked")
        if ch is None:
            ch = {
                "stage_ptrs": (ctypes.c_void_p * plan.nseg)(*[
                    0 if v is None else v.ctypes.data
                    for v in plan.stage_views]),
                "stage_codes": (ctypes.c_int * plan.nseg)(*[
                    -1 if s is None
                    else _native._DTYPE_CODES[str(s)]
                    for s in plan.seg_src_dtypes]),
            }
            plan.cache["chunked"] = ch
        rc = lib.hvd_steady_worker_chunked(
            fd, req_tag, resp_tag, c["prefix"], len(plan.prefix),
            c["hdr_ptrs"], c["hdr_lens"], send_ptrs,
            ch["stage_ptrs"], ch["stage_codes"],
            plan.chunk_bytes, recv_ptrs,
            c["seg_lens"], c["seg_codes"], plan.nseg,
            b["secret"], len(secret),
            b["skip"], b["nskip"], timeout_ms, interval_ms,
            ctypes.byref(dev_buf), ctypes.byref(dev_len),
            ctypes.byref(dev_tag))
    else:
        if plan.chunked:
            # Defensive repack outside the arena: the deferred cast
            # never ran — materialize the wire views it would target.
            plan.materialize_wire()
        rc = lib.hvd_steady_worker(
            fd, req_tag, resp_tag, c["prefix"], len(plan.prefix),
            c["hdr_ptrs"], c["hdr_lens"], send_ptrs, recv_ptrs,
            c["seg_lens"], plan.nseg, b["secret"], len(secret),
            b["skip"], b["nskip"], timeout_ms, interval_ms,
            ctypes.byref(dev_buf), ctypes.byref(dev_len),
            ctypes.byref(dev_tag))
    if rc == 0:
        return DONE, plan.result_segments(result)
    if rc == 1:
        try:
            payload = ctypes.string_at(dev_buf, dev_len.value)
        finally:
            lib.hvd_free(dev_buf)
        return FRAME, (dev_tag.value, payload)
    return ERR, rc


def _c_coord(plan: SteadyPlan, n: int, scratch: FusionArena) -> Dict:
    """Coordinator bundle: per-peer scratch segment views + pointer
    table, rebuilt when the peer count or scratch allocation moves."""
    key = ("coord", n, scratch.generation)
    b = plan.cache.get("coord")
    if b is not None and b["key"] == key:
        return b
    per_peer = sum(plan.seg_nbytes)
    scratch.ensure(n * per_peer)
    if scratch.generation != key[2]:
        key = ("coord", n, scratch.generation)
    peer_views: List[List[np.ndarray]] = []
    ptrs = (_u8p * (n * plan.nseg))()
    for i in range(n):
        off = i * per_peer
        segs = []
        for j, (npdt, nb, count) in enumerate(zip(
                plan.seg_np_dtypes, plan.seg_nbytes, plan.seg_counts)):
            v = scratch.typed(off, npdt, count)
            segs.append(v)
            ptrs[i * plan.nseg + j] = ctypes.cast(
                ctypes.c_void_p(v.ctypes.data), _u8p)
            off += nb
        peer_views.append(segs)
    b = {"key": key, "peer_views": peer_views, "peer_ptrs": ptrs}
    plan.cache["coord"] = b
    return b


def run_coord_cycle(lib, plan: SteadyPlan, fds: List[int],
                    secret: bytes, acc_bufs: List[np.ndarray],
                    skip_tags: bytes, req_tag: int, resp_tag: int,
                    hb, on_idle, scratch: FusionArena, on_oob):
    """One native steady cycle, coordinator side. ``acc_bufs`` hold
    rank 0's own packed contribution and are reduced IN PLACE into the
    world sums. ``on_oob(peer_idx, tag, payload) -> bool`` absorbs an
    out-of-band frame (metrics) — True resumes the native gather with
    the already-received frames intact. Returns
    (DONE, (acc segments, arrivals)) | (DEV, (idx, tag, payload,
    done_list, peer_views)) | (ERR, (rc, done_list)). ``arrivals`` is
    each peer's frame-completion stamp on CLOCK_MONOTONIC (0.0 for a
    frame absorbed before this call re-entered, e.g. across an
    out-of-band bounce) — the steady fast path's feed into the
    coordinator's straggler attribution."""
    n = len(fds)
    c = _c_common(plan)
    b = _c_coord(plan, n, scratch)
    # Secret/skip/fd marshalling is step-invariant (fds only change on
    # a dead channel, which the caller re-probes every cycle): cache
    # it like the worker half's bundle instead of re-copying per step.
    io_key = (tuple(fds), skip_tags)
    io = plan.cache.get("coord_io")
    if io is None or io["key"] != io_key:
        io = {"key": io_key, "sec": _mkbuf(secret),
              "skip": _mkbuf(skip_tags),
              "fds": (ctypes.c_int * n)(*fds)}
        plan.cache["coord_io"] = io
    sec = io["sec"]
    skip = io["skip"]
    fds_arr = io["fds"]
    acc_ptrs = (ctypes.c_void_p * plan.nseg)(
        *[a.ctypes.data for a in acc_bufs])
    done = (ctypes.c_uint8 * n)()
    arrive = (ctypes.c_double * n)()
    timeout_ms, interval_ms = _hb_ms(hb)
    idle_cb = on_idle if on_idle is not None else _native.NULL_ON_IDLE
    dev_idx = ctypes.c_int(-1)
    dev_buf = _u8p()
    dev_len = ctypes.c_int64()
    dev_tag = ctypes.c_uint8()
    while True:
        rc = lib.hvd_steady_coord(
            fds_arr, n, req_tag, resp_tag, c["prefix"],
            len(plan.prefix), c["hdr_ptrs"], c["hdr_lens"],
            c["seg_lens"], c["seg_codes"], plan.nseg, b["peer_ptrs"],
            acc_ptrs, sec, len(secret), skip, len(skip_tags),
            timeout_ms, interval_ms, idle_cb, done, arrive,
            ctypes.byref(dev_idx), ctypes.byref(dev_buf),
            ctypes.byref(dev_len), ctypes.byref(dev_tag))
        if rc == 0:
            return DONE, ([(dt, a) for dt, a in
                           zip(plan.seg_dtypes, acc_bufs)],
                          list(arrive))
        if rc == 1:
            try:
                payload = ctypes.string_at(dev_buf, dev_len.value)
            finally:
                lib.hvd_free(dev_buf)
            if on_oob is not None and on_oob(dev_idx.value,
                                            dev_tag.value, payload):
                continue  # absorbed (metrics): resume the gather
            return DEV, (dev_idx.value, dev_tag.value, payload,
                         list(done), b["peer_views"])
        return ERR, (rc, list(done))


def peer_frame_bytes(plan: SteadyPlan, peer_segs) -> bytes:
    """Reconstruct a peer's full CACHED_SPEC frame from its absorbed
    scratch segments — the coordinator's deviation fallback feeds
    these to the classic negotiation path (rare; a transition cycle
    pays one copy)."""
    parts = [plan.prefix]
    for h, v in zip(plan.seg_hdrs, peer_segs):
        parts.append(h)
        parts.append(memoryview(v.view(np.uint8)))
    return b"".join(parts)


# Errno helpers for the controllers' error mapping.
ETIMEDOUT = -errno.ETIMEDOUT
EBADMSG = -errno.EBADMSG
