"""Control-plane controllers: how ranks exchange Request/Response lists.

The reference's control plane is MPI on a private duplicated communicator:
each cycle, workers ``MPI_Gather`` + ``MPI_Gatherv`` their serialized
``RequestList`` to rank 0 and receive the fused ``ResponseList`` via
``MPI_Bcast`` (reference: horovod/common/operations.cc:1044-1065 and
1281-1302). A TPU pod has no MPI; this module supplies the same three
primitives — gather-to-coordinator, broadcast-from-coordinator, identity
metadata — over persistent HMAC'd TCP connections, plus a trivial
in-process controller for size-1 worlds.

The handshake also computes local/cross topology: ranks are grouped by
hostname exactly like the reference's ``MPI_Comm_split_type(SHARED)`` +
``MPI_Comm_split(local_rank)`` construction
(reference: operations.cc:729-764, run/common/util/host_hash.py).
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, List, Optional

from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network

def _my_hostname() -> str:
    """Hostname used for local/cross topology grouping. The
    HOROVOD_HOSTNAME override serves containerized ranks whose kernel
    hostname is meaningless, and lets tests force a multi-host shape
    on one machine (reference analog: host_hash's override-free
    hostname grouping, run/common/util/host_hash.py)."""
    return os.environ.get("HOROVOD_HOSTNAME") or socket.gethostname()


# Frame tags on the controller channel.
TAG_HANDSHAKE = 1
TAG_REQUESTS = 2    # worker -> coordinator: serialized RequestList
TAG_RESPONSES = 3   # coordinator -> worker: serialized ResponseList
TAG_DATA = 4        # data-plane payload (socket fallback backend)


def _as_buffer(payload):
    """Normalize a data-plane payload to a flat byte view. Callers may
    pass numpy arrays straight through (zero-copy send path); the
    control plane still deals in bytes."""
    if payload is None:
        return None
    return network.as_byte_view(payload)


class Topology:
    """World/local/cross identity of this process
    (reference: global_state.h:95-118)."""

    __slots__ = ("rank", "size", "local_rank", "local_size",
                 "cross_rank", "cross_size", "is_homogeneous",
                 "local_sizes", "local_roots")

    def __init__(self, rank: int, size: int, local_rank: int = 0,
                 local_size: int = 1, cross_rank: int = 0,
                 cross_size: int = 1, is_homogeneous: bool = True,
                 local_sizes: Optional[List[int]] = None,
                 local_roots: Optional[List[int]] = None):
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self.is_homogeneous = is_homogeneous
        self.local_sizes = local_sizes or [local_size]
        # global rank of each host's local_rank-0 process, host order
        self.local_roots = local_roots if local_roots is not None \
            else [0]


def compute_topology(rank: int, hostnames: List[str]) -> Topology:
    """Group ranks by hostname → local/cross communicator shape
    (reference: operations.cc:729-764; homogeneity check 741-757)."""
    size = len(hostnames)
    my_host = hostnames[rank]
    local_ranks = [r for r in range(size) if hostnames[r] == my_host]
    local_rank = local_ranks.index(rank)
    local_size = len(local_ranks)
    # cross communicator: one member per host, split by local_rank
    hosts_in_order: List[str] = []
    for h in hostnames:
        if h not in hosts_in_order:
            hosts_in_order.append(h)
    cross_rank = hosts_in_order.index(my_host)
    cross_size = len(hosts_in_order)
    local_sizes = [sum(1 for h in hostnames if h == host)
                   for host in hosts_in_order]
    local_roots = [hostnames.index(host) for host in hosts_in_order]
    is_homogeneous = all(s == local_sizes[0] for s in local_sizes)
    return Topology(rank=rank, size=size, local_rank=local_rank,
                    local_size=local_size, cross_rank=cross_rank,
                    cross_size=cross_size, is_homogeneous=is_homogeneous,
                    local_sizes=local_sizes, local_roots=local_roots)


class Controller:
    """Abstract control plane."""

    topology: Topology

    @property
    def rank(self) -> int:
        return self.topology.rank

    @property
    def size(self) -> int:
        return self.topology.size

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        """Coordinator: returns all ranks' serialized RequestLists
        (index = rank), including its own. Workers: send and return None."""
        raise NotImplementedError

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        """Coordinator passes the serialized ResponseList; workers pass
        None. Everyone returns the broadcast bytes."""
        raise NotImplementedError

    # Data-plane helpers for the socket fallback backend -----------------
    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        raise NotImplementedError

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        raise NotImplementedError

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        """Coordinator passes one payload per rank; every rank returns
        its own."""
        raise NotImplementedError

    def agree(self, local_flag: bool) -> bool:
        """World-wide AND of a per-rank boolean over the data channel.

        Backend-enablement decisions must be identical on every rank or
        the job deadlocks (some ranks inside an XLA collective, others
        in a socket gather). Callers must invoke this at the same point
        of the negotiated response stream on all ranks — which is
        exactly when ``CollectiveBackend.enabled`` runs."""
        gathered = self.gather_data(b"\x01" if local_flag else b"\x00")
        if gathered is not None:  # coordinator
            ok = all(g == b"\x01" for g in gathered)
            return self.broadcast_data(
                b"\x01" if ok else b"\x00") == b"\x01"
        return self.broadcast_data(None) == b"\x01"

    def close(self) -> None:
        pass


class LocalController(Controller):
    """Size-1 world: negotiation is immediate."""

    def __init__(self):
        self.topology = Topology(rank=0, size=1)

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        return [payload]

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        assert payload is not None
        return payload

    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        return [payload]

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        assert payload is not None
        return payload

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        assert payloads is not None and len(payloads) == 1
        return payloads[0]


class TcpCoordinator(Controller):
    """Rank 0: accepts one persistent connection per worker.

    Per-cycle gather/broadcast hot paths go through the native core
    (native/hvdtpu.cc hvd_gather_frames: one poll(2) loop servicing all
    workers with the GIL released) when the library is available; the
    Python per-channel loop is the fallback."""

    def __init__(self, size: int, port: int = 0, secret: bytes = b"",
                 start_timeout: float = 30.0, listener=None):
        """``listener`` — an already-bound listening socket to adopt
        instead of binding ``port``. Launch layers that must publish
        the coordinator endpoint BEFORE init (Spark rendezvous,
        hvdtpurun's per-host port reservation) hand the bound socket
        over so there is no close-then-rebind window for another
        process to steal the port."""
        self._secret = secret
        self._server = listener if listener is not None \
            else network.listen(port)
        self.port = self._server.getsockname()[1]
        self._channels: Dict[int, network.Channel] = {}
        self._hostname = _my_hostname()
        self._size = size
        self._start_timeout = start_timeout
        self.topology = None  # set by accept_workers
        self._native = None
        self._worker_fds = None  # ranks 1..size-1 in rank order

    def accept_workers(self) -> None:
        deadline = time.monotonic() + self._start_timeout
        hostnames = [None] * self._size
        hostnames[0] = self._hostname
        self._server.settimeout(1.0)
        while len(self._channels) < self._size - 1:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"Only {len(self._channels) + 1}/{self._size} ranks "
                    f"connected within start timeout; increase "
                    f"HOROVOD_START_TIMEOUT if startup is slow.")
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            # A stray probe, a garbage frame, or a worker dying
            # mid-handshake must not abort startup — reject the
            # connection and keep waiting for legitimate workers.
            try:
                sock.settimeout(5.0)
                ch = network.Channel(sock, self._secret)
                tag, payload = ch.recv()
                if tag != TAG_HANDSHAKE:
                    raise ConnectionError(f"unexpected tag {tag}")
                hello = json.loads(payload.decode())
                r = int(hello["rank"])
                host = hello["hostname"]
                if r <= 0 or r >= self._size or r in self._channels:
                    raise ConnectionError(f"bad or duplicate rank {r}")
            except (ConnectionError, socket.timeout, ValueError,
                    KeyError, TypeError, UnicodeDecodeError) as e:
                hlog.warning(f"rejected connection during startup: {e}",
                             rank=0)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.settimeout(None)
            hostnames[r] = host
            self._channels[r] = ch
        # Broadcast the full hostname list so every rank derives the same
        # topology (reference: operations.cc:729-764).
        blob = json.dumps({"hostnames": hostnames}).encode()
        for r, ch in self._channels.items():
            ch.send(blob, TAG_HANDSHAKE)
        self.topology = compute_topology(0, hostnames)
        self._init_native()
        hlog.debug(f"coordinator up: {self._size} ranks, "
                   f"{self.topology.cross_size} hosts", rank=0)

    def _init_native(self) -> None:
        from horovod_tpu import native
        lib = native.get()
        if lib is None or self._size <= 1:
            return
        import ctypes
        ranks = sorted(self._channels)
        fds = [self._channels[r].sock.fileno() for r in ranks]
        self._native = (lib, ctypes)
        self._worker_ranks = ranks
        self._worker_fds = (ctypes.c_int * len(fds))(*fds)
        self._native_secret = (ctypes.c_uint8 * max(
            1, len(self._secret))).from_buffer_copy(
                self._secret or b"\x00")

    @staticmethod
    def _as_u8(ctypes, data: bytes):
        """bytes → ctypes u8 array at memcpy speed (never a per-byte
        Python loop — these sit on the per-cycle hot path)."""
        return (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(
            data or b"\x00")

    def _native_gather(self, payload: bytes, expect_tag: int):
        lib, ctypes = self._native
        n = len(self._worker_ranks)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        bufs = (u8p * n)()
        lens = (ctypes.c_int64 * n)()
        tags = (ctypes.c_uint8 * n)()
        try:
            rc = lib.hvd_gather_frames(self._worker_fds, n,
                                       self._native_secret,
                                       len(self._secret), bufs, lens,
                                       tags, -1)
            if rc != 0:
                # partial frames may already be malloc'd; the finally
                # block frees them.
                raise ConnectionError(
                    f"native gather failed: errno {-rc}")
            out: List[bytes] = [b""] * self._size
            out[0] = payload
            for i, r in enumerate(self._worker_ranks):
                if tags[i] != expect_tag:
                    raise ConnectionError(
                        f"expected tag {expect_tag} from rank {r}, got "
                        f"{tags[i]}")
                out[r] = ctypes.string_at(bufs[i], lens[i])
        finally:
            for i in range(n):
                if bufs[i]:
                    lib.hvd_free(bufs[i])
        return out

    def _native_send_all(self, payload: bytes, tag: int,
                         exclude_rank: Optional[int] = None) -> bool:
        lib, ctypes = self._native
        if exclude_rank is None:
            fds, n = self._worker_fds, len(self._worker_ranks)
        else:
            sub = [fd for r, fd in zip(self._worker_ranks,
                                       self._worker_fds)
                   if r != exclude_rank]
            fds, n = (ctypes.c_int * len(sub))(*sub), len(sub)
        buf = self._as_u8(ctypes, payload)
        rc = lib.hvd_broadcast_frame(fds, n, tag, buf,
                                     len(payload), self._native_secret,
                                     len(self._secret))
        if rc != 0:
            raise ConnectionError(f"native broadcast failed: errno {-rc}")
        return True

    def _native_scatter(self, payloads: List[bytes]) -> None:
        """Scatter payloads[r] to worker rank r (payloads[0] is local)."""
        lib, ctypes = self._native
        n = len(self._worker_ranks)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        arrs = [self._as_u8(ctypes, payloads[r])
                for r in self._worker_ranks]
        ptrs = (u8p * n)(*[ctypes.cast(a, u8p) for a in arrs])
        lens = (ctypes.c_int64 * n)(
            *[len(payloads[r]) for r in self._worker_ranks])
        rc = lib.hvd_scatter_frames(self._worker_fds, n, TAG_DATA, ptrs,
                                    lens, self._native_secret,
                                    len(self._secret))
        if rc != 0:
            raise ConnectionError(f"native scatter failed: errno {-rc}")

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        if self._native is not None:
            return self._native_gather(payload, TAG_REQUESTS)
        out: List[bytes] = [b""] * self._size
        out[0] = payload
        for r, ch in self._channels.items():
            tag, data = ch.recv()
            if tag != TAG_REQUESTS:
                raise ConnectionError(
                    f"expected TAG_REQUESTS from rank {r}, got {tag}")
            out[r] = data
        return out

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        assert payload is not None
        if self._native is not None:
            self._native_send_all(payload, TAG_RESPONSES)
            return payload
        for ch in self._channels.values():
            ch.send(payload, TAG_RESPONSES)
        return payload

    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        payload = _as_buffer(payload)
        if self._native is not None:
            return self._native_gather(payload, TAG_DATA)
        out: List[bytes] = [b""] * self._size
        out[0] = payload
        for r, ch in self._channels.items():
            tag, data = ch.recv()
            if tag != TAG_DATA:
                raise ConnectionError(
                    f"expected TAG_DATA from rank {r}, got {tag}")
            out[r] = data
        return out

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        payload = _as_buffer(payload)
        if root_rank != 0:
            # Pull the payload up from the root, then fan out to
            # everyone EXCEPT the root — it already has the bytes, and
            # echoing them back would double the root's traffic.
            tag, payload = self._channels[root_rank].recv()
            if tag != TAG_DATA:
                raise ConnectionError("expected TAG_DATA from root")
            assert payload is not None
            if self._native is not None:
                self._native_send_all(payload, TAG_DATA,
                                      exclude_rank=root_rank)
                return payload
            for r, ch in self._channels.items():
                if r != root_rank:
                    ch.send(payload, TAG_DATA)
            return payload
        assert payload is not None
        if self._native is not None:
            self._native_send_all(payload, TAG_DATA)
            return payload
        for ch in self._channels.values():
            ch.send(payload, TAG_DATA)
        return payload

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        assert payloads is not None and len(payloads) == self._size
        if self._native is not None:
            self._native_scatter(payloads)
            return payloads[0]
        for r, ch in self._channels.items():
            ch.send(payloads[r], TAG_DATA)
        return payloads[0]

    def worker_peer_ip(self, rank: int) -> str:
        """IP of worker ``rank`` as seen from this coordinator — the
        address other ranks use to reach that worker's data listener
        (ring rendezvous, ops/ring.py)."""
        return self._channels[rank].sock.getpeername()[0]

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._server.close()


class TcpWorker(Controller):
    """Ranks 1..size-1: one persistent connection to the coordinator."""

    def __init__(self, rank: int, size: int, addr: str, port: int,
                 secret: bytes = b"", start_timeout: float = 30.0):
        self.coordinator_addr = addr  # rank 0's reachable address
        self._ch = network.connect(addr, port, secret,
                                   timeout=start_timeout,
                                   retry_deadline=start_timeout)
        hello = json.dumps({
            "rank": rank, "hostname": _my_hostname()}).encode()
        self._ch.send(hello, TAG_HANDSHAKE)
        tag, payload = self._ch.recv()
        if tag != TAG_HANDSHAKE:
            raise ConnectionError("handshake failed")
        hostnames = json.loads(payload.decode())["hostnames"]
        self.topology = compute_topology(rank, hostnames)

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        self._ch.send(payload, TAG_REQUESTS)
        return None

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        tag, data = self._ch.recv()
        if tag != TAG_RESPONSES:
            raise ConnectionError(f"expected TAG_RESPONSES, got {tag}")
        return data

    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        self._ch.send(_as_buffer(payload), TAG_DATA)
        return None

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        payload = _as_buffer(payload)
        if payload is not None and self.rank == root_rank:
            # Root sends up; the coordinator fans out to the others
            # only — our own copy is already authoritative.
            self._ch.send(payload, TAG_DATA)
            return payload
        tag, data = self._ch.recv()
        if tag != TAG_DATA:
            raise ConnectionError(f"expected TAG_DATA, got {tag}")
        return data

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        tag, data = self._ch.recv()
        if tag != TAG_DATA:
            raise ConnectionError(f"expected TAG_DATA, got {tag}")
        return data

    def close(self) -> None:
        self._ch.close()
