"""Control-plane controllers: how ranks exchange Request/Response lists.

The reference's control plane is MPI on a private duplicated communicator:
each cycle, workers ``MPI_Gather`` + ``MPI_Gatherv`` their serialized
``RequestList`` to rank 0 and receive the fused ``ResponseList`` via
``MPI_Bcast`` (reference: horovod/common/operations.cc:1044-1065 and
1281-1302). A TPU pod has no MPI; this module supplies the same three
primitives — gather-to-coordinator, broadcast-from-coordinator, identity
metadata — over persistent HMAC'd TCP connections, plus a trivial
in-process controller for size-1 worlds.

The handshake also computes local/cross topology: ranks are grouped by
hostname exactly like the reference's ``MPI_Comm_split_type(SHARED)`` +
``MPI_Comm_split(local_rank)`` construction
(reference: operations.cc:729-764, run/common/util/host_hash.py).
"""

from __future__ import annotations

import errno
import ipaddress
import json
import os
import select
import socket
import struct
import time
from typing import Dict, List, Optional

from horovod_tpu.common import config as hconfig
from horovod_tpu.common import heartbeat
from horovod_tpu.common import logging as hlog
from horovod_tpu.common import network
from horovod_tpu.common import wire
from horovod_tpu.common.status import WorldAbortedError, world_abort_message

def _my_hostname() -> str:
    """Hostname used for local/cross topology grouping. The
    HOROVOD_HOSTNAME override serves containerized ranks whose kernel
    hostname is meaningless, and lets tests force a multi-host shape
    on one machine (reference analog: host_hash's override-free
    hostname grouping, run/common/util/host_hash.py)."""
    return hconfig.env_str("HOROVOD_HOSTNAME") or socket.gethostname()


def _local_root_addr() -> str:
    """Address same-host leaf ranks use to reach their local root's
    listener (hierarchical control plane). Loopback is right whenever
    the host's ranks share a network namespace; per-rank containers
    that share only HOROVOD_HOSTNAME set HOROVOD_TPU_LOCAL_ROOT_ADDR
    to a mutually reachable address (the root binds it too)."""
    return hconfig.env_str("HOROVOD_TPU_LOCAL_ROOT_ADDR", "127.0.0.1")


def host_groups(hostnames: List[str]):
    """Group ranks by hostname in first-seen host order — THE canonical
    grouping every control-plane participant must agree on (topology,
    coordinator aggregation, local-root membership all derive from this
    one function; reference: operations.cc:729-764).

    Returns (hosts, members) with ``hosts`` the distinct hostnames in
    first-appearance order and ``members[i]`` the ascending global
    ranks on ``hosts[i]``."""
    hosts: List[str] = []
    for h in hostnames:
        if h not in hosts:
            hosts.append(h)
    members = [[r for r in range(len(hostnames)) if hostnames[r] == h]
               for h in hosts]
    return hosts, members


# Frame tags on the controller channel.
TAG_HANDSHAKE = 1
TAG_REQUESTS = 2    # worker -> coordinator: serialized RequestList
TAG_RESPONSES = 3   # coordinator -> worker: serialized ResponseList
TAG_DATA = 4        # data-plane payload (socket fallback backend)
TAG_PING = 5        # downward liveness beacon (heartbeat.encode_ping)
TAG_ABORT = 6       # world abort notice (heartbeat.encode_abort)
TAG_METRICS = 7     # upward metrics snapshot (wire.*_metrics_frame) —
                    # out-of-band like PING: absorbed wherever a
                    # control frame is awaited, never negotiated
TAG_TRACE = 8       # upward trace-span batch (wire.*_trace_frame,
                    # common/trace.py) — out-of-band like METRICS;
                    # carries the worker half of the clock-sync echo


def _dead_peers(channels: Dict[int, "network.Channel"]) -> List[int]:
    """Ranks whose channel socket is dead (hung up, errored, or
    orderly-closed), probed without blocking. Called only on failure
    paths, to turn an anonymous transport error from a fan-out
    primitive into a named origin rank."""
    dead: List[int] = []
    for r, ch in channels.items():
        try:
            fd = ch.sock.fileno()
        except OSError:
            fd = -1
        if fd < 0:
            # Locally closed (e.g. an injected sever): dead by
            # definition, and poll.register would raise on it.
            dead.append(r)
            continue
        try:
            p = select.poll()
            p.register(fd, select.POLLIN)
            events = p.poll(0)
            if not events:
                continue
            mask = events[0][1]
            if mask & (select.POLLHUP | select.POLLERR | select.POLLNVAL):
                dead.append(r)
            elif mask & select.POLLIN:
                # Readable could be a buffered frame OR an orderly
                # close; peek distinguishes without consuming.
                if ch.sock.recv(1, socket.MSG_PEEK) == b"":
                    dead.append(r)
        except OSError:
            dead.append(r)
    return dead


def _abort_error(origin: int, cause: str,
                 resolved: bool = False) -> WorldAbortedError:
    """``resolved=True`` marks an AUTHORITATIVE notice decoded off the
    wire: the runtime's failure handler then commits the origin as-is
    instead of re-draining the control plane for a better one."""
    err = WorldAbortedError(world_abort_message(origin, cause),
                            origin_rank=origin, cause=cause)
    err.resolved = resolved
    return err


def _drain_abort(channels: Dict[int, "network.Channel"],
                 grace_s: float) -> Optional[tuple]:
    """Sweep the control channels for a queued (or just-arriving,
    within ``grace_s``) TAG_ABORT notice → (origin, cause), else None.

    A locally inferred transport blame can race the authoritative
    notice from the rank that actually DETECTED the failure: its
    teardown closes channels, and to peers that close is a second,
    misattributable failure (e.g. a ring survivor names its dead
    neighbor and collapses; this rank only sees the survivor's close).
    Failure path only — never runs in a healthy world. Non-abort
    frames found in the sweep are discarded; the world is already
    dead, nothing will negotiate them."""
    deadline = time.monotonic() + grace_s
    while True:
        for ch in channels.values():
            # Bypass the channel's liveness slicing: a 50 ms cap per
            # read keeps the sweep prompt even over partial frames
            # flushed by a dying peer.
            prev_hb, ch._hb = ch._hb, None
            try:
                prev_to = ch.sock.gettimeout()
                ch.sock.settimeout(0.05)
                try:
                    p = select.poll()
                    p.register(ch.sock.fileno(), select.POLLIN)
                    while p.poll(0):
                        tag, data = ch.recv()
                        if tag == TAG_ABORT:
                            return heartbeat.decode_abort(data)
                finally:
                    ch.sock.settimeout(prev_to)
            except (OSError, ValueError):
                pass  # dead/garbled channel: nothing to learn here
            finally:
                ch._hb = prev_hb
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.02)


def _maybe_ping(ctl, channels: Dict[int, "network.Channel"],
                sender_rank: int) -> None:
    """Shared PING fan-out for both tree tiers (coordinator → owners,
    local root → leaves): rate-limited to the controller's configured
    interval (idle slices can tick faster — see _NativeFanout), send
    failures swallowed (the recv/abort paths own the reporting)."""
    now = time.monotonic()
    if now - ctl._last_ping < _ping_interval(ctl._hb_timeout,
                                            ctl._hb_interval):
        return
    ctl._last_ping = now
    ctl._ping_seq += 1
    if sender_rank == 0 and not getattr(ctl, "_world_id", 0):
        # Clock-sync t1: the coordinator clock is the world's
        # reference frame, so only rank 0's beacons are recorded
        # (common/trace.py ClockSync; local-root beacons carry their
        # own clocks and would poison the table — and so would a
        # TENANT sub-world's coordinator, whose ping sequence is a
        # different stream than the default world's).
        from horovod_tpu.common import trace as htrace
        htrace.clock().ping_sent(ctl._ping_seq, now)
    payload = heartbeat.encode_ping(sender_rank, ctl._ping_seq)
    for ch in channels.values():
        try:
            ch.send(payload, TAG_PING)
        except OSError:
            pass


def _hb_normalized(timeout_s: float, interval_s: float) -> tuple:
    """(timeout_s, interval_s) with the interval clamped into
    (0, timeout/2] — same normalization Channel.arm applies, so the
    native fanout's slice loop can't busy-poll on interval<=0,
    overshoot the deadline by a whole oversized interval, or tick
    on_idle (the PING beacon) fewer than twice per peer deadline
    window."""
    half = timeout_s / 2.0
    interval_s = min(interval_s, half) if interval_s > 0 else half
    return timeout_s, interval_s


def _ping_interval(timeout_s: float, interval_s: float) -> float:
    """The PING send gate must beacon at least twice per deadline
    window regardless of the configured interval — gating on a raw
    interval >= the timeout would starve every waiting receiver of
    proof-of-life and falsely abort a healthy world."""
    half = timeout_s / 2.0
    return min(interval_s, half) if interval_s > 0 else half


_PACK_COUNT = struct.Struct("<I")
_PACK_LEN = struct.Struct("<Q")


def pack_frames(frames: List[bytes]) -> bytes:
    """Concatenate several per-rank frames into one aggregate payload
    (hierarchical control plane: a host's local root forwards ONE frame
    carrying all its ranks' messages — the control-plane rendering of
    the reference's LOCAL-then-CROSS communicator split,
    reference: horovod/common/operations.cc:729-764)."""
    parts = [_PACK_COUNT.pack(len(frames))]
    for f in frames:
        parts.append(_PACK_LEN.pack(len(f)))
        parts.append(bytes(f) if not isinstance(f, (bytes, bytearray))
                     else f)
    return b"".join(parts)


def unpack_frames(blob: bytes) -> List[bytes]:
    """Inverse of :func:`pack_frames`. An aggregate truncated
    mid-header raises ConnectionError like every other malformed
    control frame — the relay error handling (and the fail-fast blame
    machinery behind it) is written around the ConnectionError family,
    and a raw ``struct.error`` would escape it."""
    try:
        (n,) = _PACK_COUNT.unpack_from(blob, 0)
        off = _PACK_COUNT.size
        out: List[bytes] = []
        for _ in range(n):
            (ln,) = _PACK_LEN.unpack_from(blob, off)
            off += _PACK_LEN.size
            if off + ln > len(blob):
                raise ConnectionError(
                    f"aggregate frame truncated: slot of {ln} bytes "
                    f"at offset {off} overruns {len(blob)}-byte blob")
            out.append(bytes(blob[off:off + ln]))
            off += ln
    except struct.error as e:
        raise ConnectionError(
            f"aggregate frame truncated mid-header: {e}") from e
    if off != len(blob):
        raise ConnectionError(
            f"aggregate frame has {len(blob) - off} trailing bytes")
    return out


def _dialable_leaf_ip(ip: str) -> bool:
    """True when a leaf's observed connect address is worth recording
    as its dialable override. Loopback means shared-netns (the root
    channel's IP answers for the leaf) — and that includes IPv6
    ``::1``, which a prefix test on ``127.`` would wrongly record as
    a dialable address. Unparseable strings stay excluded."""
    try:
        return not ipaddress.ip_address(ip).is_loopback
    except ValueError:
        return False


def _accept_handshakes(server, secret: bytes, deadline: float,
                       timeout_msg, validate):
    """Shared hardened accept loop (coordinator startup and local-root
    leaf rendezvous): accept, handshake, validate; a stray probe, a
    garbage frame, or a peer dying mid-handshake is rejected without
    aborting startup. ``validate(hello) -> rank`` raises
    ConnectionError (or Key/Value/TypeError) to reject; ``timeout_msg``
    is a callable so the error reflects progress at expiry. Yields
    (rank, hello, channel) per accepted peer, forever — the caller
    stops iterating when it has everyone."""
    server.settimeout(1.0)
    while True:
        if time.monotonic() > deadline:
            raise TimeoutError(timeout_msg())
        try:
            sock, _ = server.accept()
        except socket.timeout:
            continue
        try:
            sock.settimeout(5.0)
            ch = network.Channel(sock, secret)
            tag, payload = ch.recv()
            if tag != TAG_HANDSHAKE:
                raise ConnectionError(f"unexpected tag {tag}")
            hello = json.loads(payload.decode())
            r = validate(hello)
        except (ConnectionError, socket.timeout, ValueError,
                KeyError, TypeError, UnicodeDecodeError) as e:
            hlog.warning(f"rejected connection during startup: {e}")
            try:
                sock.close()
            except OSError:
                pass
            continue
        sock.settimeout(None)
        yield r, hello, ch


class _NativeFanout:
    """poll(2)-based frame gather/broadcast/scatter over a fixed set of
    peer channels through the native core (native/hvdtpu.cc, GIL
    released) — the per-cycle hot path shared by the coordinator (its
    worker channels) and by hierarchical local roots (their leaf
    children). :meth:`create` returns None when the native library is
    unavailable or there are no peers; callers then fall back to the
    per-channel Python loops."""

    def __init__(self, lib, ctypes_mod, channels: Dict[int, "network.Channel"],
                 secret: bytes, hb=None, on_metrics=None,
                 on_trace=None):
        self._lib = lib
        self._ct = ctypes_mod
        # callable(rank, payload) fired when a TAG_METRICS frame
        # arrives in a gather slice (the sender stays pending — its
        # real cycle frame is still owed). None drops such frames.
        self._on_metrics = on_metrics
        # Same contract for TAG_TRACE frames (common/trace.py).
        self._on_trace = on_trace
        # rank -> CLOCK_MONOTONIC completion stamp of its frame in
        # the LAST gather (from the native arrive array) — read by
        # the coordinator's straggler attribution right after
        # gather() returns; reset per gather.
        self.last_arrivals: Dict[int, float] = {}
        self.ranks = sorted(channels)
        fds = [channels[r].sock.fileno() for r in self.ranks]
        self._fd_list = fds
        self._fds = (ctypes_mod.c_int * len(fds))(*fds)
        self._secret = secret
        self._secret_buf = (ctypes_mod.c_uint8 * max(
            1, len(secret))).from_buffer_copy(secret or b"\x00")
        # (timeout_s, interval_s, on_idle) liveness deadline for gather,
        # or None: the native poll loop then waits in interval slices,
        # firing on_idle (PING fan-out) per idle slice and failing after
        # timeout_s of total silence — same semantics as Channel.arm.
        # The slice is additionally capped at timeout/(2*fan_in): the
        # native call keeps absorbing frames as long as each arrives
        # within one slice and only returns to Python (where on_idle
        # can run) after a fully idle slice, so a trickle of fan_in
        # frames can starve PINGs for at most fan_in*slice <= timeout/2
        # — keeping every waiting peer's own recv deadline safe.
        if hb is not None:
            timeout_s, interval_s, on_idle = hb
            interval_s = min(interval_s,
                             timeout_s / (2.0 * max(1, len(fds))))
            hb = (timeout_s, interval_s, on_idle)
        self._hb = hb
        # Lazily-built ctypes ON_IDLE thunk for the batched reactor
        # (gather_into); cached so the callback object outlives the
        # native calls that fire it.
        self._on_idle_c = None

    @classmethod
    def create(cls, channels, secret: bytes, hb=None, on_metrics=None,
               on_trace=None):
        if not channels:
            return None
        from horovod_tpu import native
        lib = native.get()
        if lib is None:
            return None
        import ctypes
        return cls(lib, ctypes, channels, secret, hb=hb,
                   on_metrics=on_metrics, on_trace=on_trace)

    def _as_u8(self, data):
        """bytes/buffer → ctypes u8 array at memcpy speed (never a
        per-byte Python loop — these sit on the per-cycle hot path).
        Empty-vs-nonempty is decided by len(), never truthiness — a
        numpy payload's __bool__ raises on multi-element arrays."""
        if not len(data):
            return (self._ct.c_uint8 * 1)(0)
        return (self._ct.c_uint8 * len(data)).from_buffer_copy(data)

    def gather(self, expect_tag: int) -> Dict[int, bytes]:
        """One frame from every peer; returns {rank: payload}. With a
        liveness deadline set, the native poll loop runs in interval
        slices: frames already received in a slice are harvested (the
        peers that delivered them are not re-polled), on_idle fires per
        empty slice, and total silence past the timeout raises. A
        TAG_ABORT frame from any peer surfaces as WorldAbortedError."""
        ct = self._ct
        u8p = ct.POINTER(ct.c_uint8)
        out: Dict[int, bytes] = {}
        self.last_arrivals = {}
        pending = list(range(len(self.ranks)))
        if self._hb is None:
            timeout_ms, deadline = -1, None
            timeout_s = interval_s = 0.0
            on_idle = None
        else:
            timeout_s, interval_s, on_idle = self._hb
            timeout_ms = max(1, int(interval_s * 1000))
            deadline = time.monotonic() + timeout_s
        while pending:
            n = len(pending)
            fds = (ct.c_int * n)(*[self._fd_list[i] for i in pending])
            bufs = (u8p * n)()
            lens = (ct.c_int64 * n)()
            tags = (ct.c_uint8 * n)()
            arrive = (ct.c_double * n)()
            still: List[int] = []
            absorbed = False  # out-of-band frames harvested this slice
            try:
                rc = self._lib.hvd_gather_frames(
                    fds, n, self._secret_buf, len(self._secret),
                    bufs, lens, tags, timeout_ms, arrive)
                if rc in (-errno.EAGAIN, -errno.EWOULDBLOCK) \
                        and self._hb is not None:
                    # SO_RCVTIMEO (armed by Channel.arm on these same
                    # fds) fired inside the native blocking read: a
                    # peer stalled MID-FRAME — poll saw readability
                    # but the rest of the frame never arrived within
                    # the heartbeat timeout. The native call doesn't
                    # report WHICH fd timed out, so only blame a rank
                    # when it's unambiguous; otherwise origin=-1
                    # ("unknown rank") with the candidates in the
                    # cause — naming a possibly-healthy peer in the
                    # machine-readable field would be worse.
                    waiting = [self.ranks[i] for i in pending]
                    origin = waiting[0] if len(waiting) == 1 else -1
                    raise _abort_error(
                        origin,
                        f"peer stalled mid-frame (silent for "
                        f"{timeout_s:g}s with a frame outstanding; "
                        f"candidates: rank(s) {waiting}) — presumed "
                        f"dead (heartbeat timeout)")
                if rc != 0 and rc != -errno.ETIMEDOUT:
                    # partial frames may already be malloc'd; the
                    # finally block frees them.
                    raise ConnectionError(
                        f"native gather failed: errno {-rc}")
                for j, i in enumerate(pending):
                    r = self.ranks[i]
                    if not bufs[j]:
                        still.append(i)
                        continue
                    if tags[j] == TAG_ABORT:
                        origin, cause = heartbeat.decode_abort(
                            ct.string_at(bufs[j], lens[j]))
                        raise _abort_error(origin, cause, resolved=True)
                    if tags[j] == TAG_METRICS:
                        # Out-of-band observability frame: absorb it
                        # and keep the sender pending — its real cycle
                        # frame is still owed this gather. It also
                        # counts as proof of life (the frame's arrival
                        # resets the silence window below).
                        if self._on_metrics is not None:
                            self._on_metrics(r, ct.string_at(bufs[j],
                                                             lens[j]))
                        absorbed = True
                        still.append(i)
                        continue
                    if tags[j] == TAG_TRACE:
                        # Same out-of-band contract as METRICS: absorb
                        # (or drop, without a sink) and keep the
                        # sender pending.
                        if self._on_trace is not None:
                            self._on_trace(r, ct.string_at(bufs[j],
                                                           lens[j]))
                        absorbed = True
                        still.append(i)
                        continue
                    if tags[j] != expect_tag:
                        raise ConnectionError(
                            f"expected tag {expect_tag} from rank {r}, "
                            f"got {tags[j]}")
                    out[r] = ct.string_at(bufs[j], lens[j])
                    if arrive[j]:
                        self.last_arrivals[r] = arrive[j]
            finally:
                for j in range(n):
                    if bufs[j]:
                        self._lib.hvd_free(bufs[j])
            if rc == -errno.ETIMEDOUT:
                if on_idle is not None:
                    on_idle()
                if len(still) != len(pending) or absorbed:
                    # some frames landed this slice (cycle frames, or
                    # absorbed out-of-band metrics): the world is
                    # moving — restart the silence window
                    deadline = time.monotonic() + timeout_s
                elif time.monotonic() > deadline:
                    # The gather knows exactly which ranks were silent
                    # — name the first as the abort origin (a merely
                    # wedged peer has a live socket, so the generic
                    # _dead_peers probe upstream would find nothing).
                    waiting = [self.ranks[i] for i in still]
                    raise _abort_error(
                        waiting[0],
                        f"no control frame from rank(s) {waiting} for "
                        f"{timeout_s:g}s — peer presumed dead "
                        f"(heartbeat timeout; raise "
                        f"HOROVOD_HEARTBEAT_TIMEOUT if peers "
                        f"legitimately stall longer)")
            pending = still
        return out

    def send_all(self, payload, tag: int,
                 exclude_rank: Optional[int] = None) -> None:
        ct = self._ct
        if exclude_rank is None:
            fd_list = self._fd_list
            fds, n = self._fds, len(self.ranks)
        else:
            fd_list = [fd for r, fd in zip(self.ranks, self._fds)
                       if r != exclude_rank]
            fds, n = (ct.c_int * len(fd_list))(*fd_list), len(fd_list)
        # Large frames (the coordinator's world blobs) ride the
        # MSG_ZEROCOPY leg when the threshold is armed — pages pinned
        # per send instead of copied into every peer's socket buffer.
        hb = self._hb
        if network.zc_fanout_send(
                self._lib, fd_list, tag, payload, self._secret_buf,
                len(self._secret),
                int(hb[0] * 1000) if hb is not None else -1):
            return
        buf = self._as_u8(payload)
        rc = self._lib.hvd_broadcast_frame(
            fds, n, tag, buf, len(payload), self._secret_buf,
            len(self._secret))
        if rc != 0:
            raise ConnectionError(f"native broadcast failed: errno {-rc}")

    def scatter(self, per_rank: Dict[int, bytes], tag: int) -> None:
        """Send per_rank[r] to each peer r."""
        ct = self._ct
        n = len(self.ranks)
        u8p = ct.POINTER(ct.c_uint8)
        arrs = [self._as_u8(per_rank[r]) for r in self.ranks]
        ptrs = (u8p * n)(*[ct.cast(a, u8p) for a in arrs])
        lens = (ct.c_int64 * n)(
            *[len(per_rank[r]) for r in self.ranks])
        rc = self._lib.hvd_scatter_frames(
            self._fds, n, tag, ptrs, lens, self._secret_buf,
            len(self._secret))
        if rc != 0:
            raise ConnectionError(f"native scatter failed: errno {-rc}")

    # -- batched-submission reactor (docs/performance.md Layer 6) --------
    @property
    def batched_ok(self) -> bool:
        """True when the loaded core exports the batched reactor entry
        (a stale pre-reactor .so simply keeps the sequential path)."""
        return hasattr(self._lib, "hvd_gather_frames_batched")

    def gather_into(self, expect_tag: int, views: Dict[int, object]):
        """One frame per peer straight into caller-owned writable
        buffers via the batched-submission reactor
        (hvd_gather_frames_batched): readiness across every channel is
        discovered in ONE submission per wakeup (io_uring when the
        build and kernel carry it, poll(2) otherwise) and each ready
        frame is read to completion in C with the GIL released — the
        recv-into mirror of :meth:`gather`, minus the per-slice
        malloc/copy round-trips. Out-of-band frames keep the exact
        _recv_data_into semantics: PINGs are absorbed in C,
        METRICS/TRACE bounce out as deviations, are dispatched here
        and the call resumes with the done[] map intact (a peer's
        delivered frame is never re-read). Returns
        ``({rank: length}, {rank: arrive stamp}, [frames-per-wakeup])``.
        """
        ct = self._ct
        from horovod_tpu import native as _native
        n = len(self.ranks)
        order = self.ranks
        mvs = [memoryview(network.as_byte_view(views[r]))
               for r in order]
        # Writable ctypes windows over the caller buffers: kept in a
        # list so the pointers stay live across the (possibly
        # re-entered) native call.
        wins = [(ct.c_uint8 * len(mv)).from_buffer(mv) if len(mv)
                else (ct.c_uint8 * 1)() for mv in mvs]
        bufs = (ct.c_void_p * n)(*[ct.addressof(w) for w in wins])
        caps = (ct.c_int64 * n)(*[len(mv) for mv in mvs])
        lens = (ct.c_int64 * n)()
        done = (ct.c_uint8 * n)()
        arrive = (ct.c_double * n)()
        batch_sizes = (ct.c_int32 * n)()
        nbatches = ct.c_int(0)
        dev_idx = ct.c_int(-1)
        dev_buf = ct.POINTER(ct.c_uint8)()
        dev_len = ct.c_int64(0)
        dev_tag = ct.c_uint8(0)
        skip = (ct.c_uint8 * 1)(TAG_PING)
        if self._hb is None:
            timeout_ms = interval_ms = -1
            timeout_s = 0.0
            on_idle_c = ct.cast(None, _native.ON_IDLE_FUNC)
        else:
            timeout_s, interval_s, on_idle = self._hb
            timeout_ms = max(1, int(timeout_s * 1000))
            interval_ms = max(1, int(interval_s * 1000))
            if self._on_idle_c is None:
                # The ctypes thunk must outlive every native call that
                # may fire it — cache it for the fanout's lifetime.
                self._on_idle_c = _native.ON_IDLE_FUNC(on_idle)
            on_idle_c = self._on_idle_c
        while True:
            rc = self._lib.hvd_gather_frames_batched(
                self._fds, n, self._secret_buf, len(self._secret),
                expect_tag, bufs, caps, lens, skip, 1,
                timeout_ms, interval_ms, on_idle_c, done, arrive,
                batch_sizes, ct.byref(nbatches), ct.byref(dev_idx),
                ct.byref(dev_buf), ct.byref(dev_len),
                ct.byref(dev_tag))
            if rc == 0:
                break
            if rc == 1:
                # Deviation: one authenticated non-PING, non-expected
                # frame was pulled off a peer; dispatch it and resume
                # the batch (the peer stays pending — its real frame
                # is still owed, exactly like _recv_data_into).
                r = order[dev_idx.value]
                tag = dev_tag.value
                if dev_buf:
                    payload = ct.string_at(dev_buf, dev_len.value)
                    self._lib.hvd_free(dev_buf)
                    dev_buf = ct.POINTER(ct.c_uint8)()
                else:
                    payload = b""
                if tag == TAG_METRICS:
                    if self._on_metrics is not None:
                        self._on_metrics(r, payload)
                    continue
                if tag == TAG_TRACE:
                    if self._on_trace is not None:
                        self._on_trace(r, payload)
                    continue
                if tag == TAG_ABORT:
                    origin, cause = heartbeat.decode_abort(payload)
                    raise _abort_error(origin, cause, resolved=True)
                if tag != expect_tag:
                    raise ConnectionError(
                        f"expected tag {expect_tag} from rank {r}, "
                        f"got {tag}")
                # expect_tag but drained to the spill: the frame
                # overflowed its preallocated buffer.
                raise ConnectionError(
                    f"data frame of {dev_len.value} bytes from rank "
                    f"{r} overflows {caps[dev_idx.value]}-byte buffer")
            if rc == -errno.ETIMEDOUT:
                waiting = [order[i] for i in range(n) if not done[i]]
                raise _abort_error(
                    waiting[0] if waiting else -1,
                    f"no control frame from rank(s) {waiting} for "
                    f"{timeout_s:g}s — peer presumed dead (heartbeat "
                    f"timeout; raise HOROVOD_HEARTBEAT_TIMEOUT if "
                    f"peers legitimately stall longer)")
            i = dev_idx.value
            if 0 <= i < n:
                r = order[i]
                raise _abort_error(
                    r, f"control channel to rank {r} failed during "
                       f"the batched gather: errno {-rc}")
            raise ConnectionError(
                f"batched native gather failed: errno {-rc}")
        out = {r: int(lens[i]) for i, r in enumerate(order)}
        self.last_arrivals = {r: arrive[i]
                              for i, r in enumerate(order) if arrive[i]}
        return out, self.last_arrivals, \
            list(batch_sizes[:min(nbatches.value, n)])


def _as_buffer(payload):
    """Normalize a data-plane payload to a flat byte view. Callers may
    pass numpy arrays straight through (zero-copy send path); the
    control plane still deals in bytes."""
    if payload is None:
        return None
    return network.as_byte_view(payload)


# Cut-through chunk size for the hierarchical relay legs
# (hvd_relay_frame): a local root forwards each chunk downstream as it
# arrives, so a leaf's read of chunk i overlaps the root's read of
# chunk i+1 and the per-hop latency approaches max(up, down) instead
# of up + down. 256 KiB keeps the resident window small while still
# amortizing syscalls on multi-MB broadcast payloads.
_RELAY_CHUNK_BYTES = 256 * 1024


def relay_frame_into(up_ch: "network.Channel",
                     child_chs: List["network.Channel"],
                     expect_tag: int, out,
                     timeout_ms: int = -1,
                     interval_ms: int = -1) -> int:
    """Receive one exact-fit frame from ``up_ch`` into ``out`` while
    cut-through forwarding it to every channel in ``child_chs``
    (hvd_relay_frame, the same native leg the hierarchical control
    plane rides). Falls back to recv_into + sendv store-and-forward
    when the native core is absent. Standalone variant of
    ``_relay_up_to_children`` for ephemeral trees (the elastic rejoin
    sync in common/selfop.py) that have Channels but no controller.
    Returns the frame's byte length."""
    mv = memoryview(network.as_byte_view(out))
    from horovod_tpu import native as _native
    lib = _native.get()
    if child_chs and lib is not None and hasattr(lib, "hvd_relay_frame"):
        import ctypes as ct
        win = (ct.c_uint8 * len(mv)).from_buffer(mv) if len(mv) \
            else (ct.c_uint8 * 1)()
        child_fds = (ct.c_int * len(child_chs))(
            *[ch.sock.fileno() for ch in child_chs])
        secret = up_ch.secret or b""
        sbuf = (ct.c_uint8 * max(1, len(secret))).from_buffer_copy(
            secret or b"\x00")
        skip = (ct.c_uint8 * 1)(0xFF)  # no stray tags on a private tree
        out_len = ct.c_int64(0)
        out_tag = ct.c_uint8(0)
        spill = ct.POINTER(ct.c_uint8)()
        rc = lib.hvd_relay_frame(
            up_ch.sock.fileno(), child_fds, len(child_chs), expect_tag,
            ct.addressof(win), len(mv), sbuf, len(secret),
            skip, 0, _RELAY_CHUNK_BYTES, timeout_ms, interval_ms,
            ct.byref(out_len), ct.byref(out_tag), ct.byref(spill))
        if spill:
            lib.hvd_free(spill)
        if rc == 0:
            return out_len.value
        if rc == 1:
            raise ConnectionError(
                f"frame of {out_len.value} bytes from {up_ch.peer} "
                f"overflows {len(mv)}-byte relay buffer")
        if rc == 2:
            raise ConnectionError(
                f"expected tag {expect_tag} from {up_ch.peer}, got "
                f"{out_tag.value}")
        raise ConnectionError(
            f"relay from {up_ch.peer} failed: errno {-rc}")
    tag, n = up_ch.recv_into(mv)
    if tag != expect_tag:
        raise ConnectionError(
            f"expected tag {expect_tag} from {up_ch.peer}, got {tag}")
    for ch in child_chs:
        ch.sendv((mv[:n],), expect_tag)
    return n


class Topology:
    """World/local/cross identity of this process
    (reference: global_state.h:95-118)."""

    __slots__ = ("rank", "size", "local_rank", "local_size",
                 "cross_rank", "cross_size", "is_homogeneous",
                 "local_sizes", "local_roots")

    def __init__(self, rank: int, size: int, local_rank: int = 0,
                 local_size: int = 1, cross_rank: int = 0,
                 cross_size: int = 1, is_homogeneous: bool = True,
                 local_sizes: Optional[List[int]] = None,
                 local_roots: Optional[List[int]] = None):
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self.is_homogeneous = is_homogeneous
        self.local_sizes = local_sizes or [local_size]
        # global rank of each host's local_rank-0 process, host order
        self.local_roots = local_roots if local_roots is not None \
            else [0]


def compute_topology(rank: int, hostnames: List[str]) -> Topology:
    """Group ranks by hostname → local/cross communicator shape
    (reference: operations.cc:729-764; homogeneity check 741-757)."""
    size = len(hostnames)
    my_host = hostnames[rank]
    hosts, members = host_groups(hostnames)
    cross_rank = hosts.index(my_host)
    cross_size = len(hosts)
    local_ranks = members[cross_rank]
    local_rank = local_ranks.index(rank)
    local_size = len(local_ranks)
    local_sizes = [len(ms) for ms in members]
    local_roots = [ms[0] for ms in members]
    is_homogeneous = all(s == local_sizes[0] for s in local_sizes)
    return Topology(rank=rank, size=size, local_rank=local_rank,
                    local_size=local_size, cross_rank=cross_rank,
                    cross_size=cross_size, is_homogeneous=is_homogeneous,
                    local_sizes=local_sizes, local_roots=local_roots)


class Controller:
    """Abstract control plane."""

    topology: Topology

    # -- metrics plane (common/metrics.py) -------------------------------
    # Rank-0 sink for METRICS frames arriving off the control tree:
    # callable(owner_rank, payload). Set by the runtime once its
    # WorldAggregator exists; frames arriving earlier are dropped
    # (best-effort totals — the next interval resends them).
    metrics_sink = None
    # -- world trace plane (common/trace.py) -----------------------------
    # Rank-0 sink for TRACE frames: callable(owner_rank, payload),
    # set by the runtime once its WorldTraceWriter exists. TAG_TRACE
    # frames are absorbed on EVERY recv path regardless (dropped
    # without a sink) — a rank with tracing armed must never be able
    # to kill a world whose coordinator has it off.
    trace_sink = None
    # True once attach_trace ran: workers then note coordinator PINGs
    # for the clock-sync echo (an extra decode per rare ping).
    _trace_on = False
    # Rank-0 arrival hook: callable({rank: monotonic stamp}) fired
    # per negotiation gather when the runtime armed straggler
    # attribution (metrics or trace plane on). None keeps the
    # disabled gather free of clock reads.
    _on_arrivals = None

    def attach_trace(self, on_arrivals=None) -> None:
        """Arm trace-plane hooks: worker-side PING noting (clock
        echo), and — on the coordinator — per-gather arrival stamps
        fed to ``on_arrivals``."""
        self._trace_on = True
        if on_arrivals is not None:
            self._on_arrivals = on_arrivals

    def send_trace(self, payload: bytes) -> None:
        """Best-effort upward TRACE frame (workers; a hierarchical
        local root concatenates its host's sections first). Never
        raises — same contract as send_metrics."""
    # Control-plane byte counters + liveness tracking, installed by
    # attach_metrics. The class-attribute defaults keep every
    # unattached (metrics-off) path at a no-op method call.
    # hvdlint: owned-by=main -- installed exactly once by attach_metrics during rendezvous, before any cycle or background thread exists (Thread.start happens-before publishes the counters); never rebound after
    _m_ctrl_rx = None
    _m_ctrl_tx = None
    _metrics_on = False
    # Batched-submission reactor (docs/performance.md Layer 6):
    # enabled by default; the runtime overrides from
    # HOROVOD_TPU_REACTOR so one rank can opt out and the world stays
    # wire byte-identical (the knob only picks this rank's LOCAL recv
    # discipline).
    _reactor = True
    # Frames completed per reactor wakeup (histogram); None until
    # attach_metrics runs — the unattached path records nothing.
    _m_reactor_batch = None

    def attach_metrics(self, registry) -> None:
        """Install control-plane instrumentation from the runtime's
        registry (a no-op registry hands back no-op metrics, keeping
        the disabled path free)."""
        self._m_ctrl_rx = registry.counter(
            'hvd_control_bytes_total{direction="rx"}',
            "control-plane bytes received by this rank")
        self._m_ctrl_tx = registry.counter(
            'hvd_control_bytes_total{direction="tx"}',
            "control-plane bytes sent by this rank")
        # Reactor observability: how many frames each batched wakeup
        # delivered (1s everywhere = the reactor is engaged but the
        # world trickles; missing series = sequential fallback), plus
        # the MSG_ZEROCOPY send counters maintained by the channel
        # layer's module hooks (network.py — a genuinely zero-copy
        # send ticks sends only; sends == copied means the kernel
        # degraded every one to a copy, e.g. loopback).
        self._m_reactor_batch = registry.histogram(
            "hvd_reactor_batch_size",
            "frames completed per batched-reactor wakeup",
            [1, 2, 4, 8, 16, 32])
        network.attach_zerocopy_metrics(
            registry.counter(
                "hvd_zerocopy_sends_total",
                "frames sent with MSG_ZEROCOPY by this rank"),
            registry.counter(
                "hvd_zerocopy_copied_total",
                "MSG_ZEROCOPY completions the kernel degraded to a "
                "plain copy"))
        self._metrics_on = bool(registry.enabled)

    def send_metrics(self, payload: bytes) -> None:
        """Best-effort upward METRICS frame (workers; a hierarchical
        local root folds its host's latest frames in first). Never
        raises — observability must not take the control plane down;
        a dead channel is the cycle path's to report."""

    def peer_heartbeat_ages(self) -> Dict[int, float]:
        """Seconds since the last control frame from each directly
        connected peer (owner channels for the coordinator, upward
        peer + leaves for workers). Only maintained while metrics are
        attached; empty otherwise."""
        return {}

    @property
    def rank(self) -> int:
        return self.topology.rank

    @property
    def size(self) -> int:
        return self.topology.size

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        """Coordinator: returns all ranks' serialized RequestLists
        (index = rank), including its own. Workers: send and return None."""
        raise NotImplementedError

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        """Coordinator passes the serialized ResponseList; workers pass
        None. Everyone returns the broadcast bytes."""
        raise NotImplementedError

    # Data-plane helpers for the socket fallback backend -----------------
    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        raise NotImplementedError

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        raise NotImplementedError

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        """Coordinator passes one payload per rank; every rank returns
        its own."""
        raise NotImplementedError

    # -- zero-copy data plane (recv-into variants) -----------------------
    # The *_into primitives move payloads straight between sockets and
    # caller-owned writable buffers (numpy arrays, arena views): no
    # bytes object is materialized on the receive side. Callers must
    # invoke them at the same negotiated response position on every
    # rank, exactly like their bytes-returning counterparts.

    def gather_data_into(self, payload, outs) -> Optional[List[int]]:
        """Data gather with preallocated receive buffers: workers send
        ``payload`` (``outs`` ignored; returns None); the coordinator
        receives rank r's payload straight into ``outs[r]`` (writable;
        ``outs[0]`` untouched — its own payload is already local) and
        returns per-rank byte counts."""
        raise NotImplementedError

    def broadcast_data_into(self, payload, out, root_rank: int = 0) -> int:
        """Broadcast with the receive side landing in ``out``: the
        root sends ``payload`` (its result is its own buffer); every
        other rank fills ``out`` and gets the byte count back."""
        raise NotImplementedError

    def scatter_data_into(self, payloads, out) -> int:
        """Scatter with the receive side landing in ``out``. The
        coordinator passes one payload per rank and only sends (its
        own slice is already local; returns its byte count); workers
        pass None and receive into ``out``."""
        raise NotImplementedError

    # -- native steady cycle (common/steady.py) --------------------------
    def steady_native_ready(self) -> bool:
        """True when this controller can run the one-call native
        steady fused cycle (flat topology tier + native core loaded).
        Stable after startup — the runtime probes once."""
        return False

    def steady_spec_cycle(self, plan, bufs):
        """Run one steady fused cycle natively (see common/steady.py).
        Returns None when unsupported (caller serializes classically),
        ('done', result_segments) on a completed single-round cycle,
        ('frame', payload) on a worker-side deviation (the broadcast
        frame to parse classically), or ('fallback', gathered) on a
        coordinator-side deviation (rank-indexed request frames for
        the classic negotiation). Transport failures raise the same
        WorldAbortedError family as the classic primitives."""
        return None

    def agree(self, local_flag: bool) -> bool:
        """World-wide AND of a per-rank boolean over the data channel.

        Backend-enablement decisions must be identical on every rank or
        the job deadlocks (some ranks inside an XLA collective, others
        in a socket gather). Callers must invoke this at the same point
        of the negotiated response stream on all ranks — which is
        exactly when ``CollectiveBackend.enabled`` runs."""
        gathered = self.gather_data(b"\x01" if local_flag else b"\x00")
        if gathered is not None:  # coordinator
            ok = all(g == b"\x01" for g in gathered)
            return self.broadcast_data(
                b"\x01" if ok else b"\x00") == b"\x01"
        return self.broadcast_data(None) == b"\x01"

    def abort(self, origin_rank: int, cause: str) -> None:
        """Best-effort fan-out of a world ABORT notice to every peer
        this controller talks to directly (coordinator: all owner
        channels; worker: upward + local leaves). Never raises — it
        runs on failure paths where channels may already be dead."""

    def sever_connection(self, target_rank: Optional[int] = None) -> None:
        """Fault injection: abruptly close a control channel (to
        ``target_rank`` when this controller owns several, else the
        upward/all channels), simulating link loss."""

    def drain_abort_notice(self, grace_s: float = 0.0) -> Optional[tuple]:
        """Failure path only: sweep this controller's channels for a
        queued TAG_ABORT → (origin_rank, cause), waiting up to
        ``grace_s`` for one in flight. Lets a rank that inferred a
        blame from an anonymous transport error defer to the
        authoritative notice from the rank that actually detected the
        failure (see _drain_abort)."""
        return None

    def close(self) -> None:
        pass


class LocalController(Controller):
    """Size-1 world: negotiation is immediate."""

    def __init__(self):
        self.topology = Topology(rank=0, size=1)

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        return [payload]

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        assert payload is not None
        return payload

    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        return [payload]

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        assert payload is not None
        return payload

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        assert payloads is not None and len(payloads) == 1
        return payloads[0]

    def gather_data_into(self, payload, outs) -> Optional[List[int]]:
        return [len(_as_buffer(payload))]

    def broadcast_data_into(self, payload, out, root_rank: int = 0) -> int:
        view = _as_buffer(payload)
        if out is not None and view is not None:
            mv = memoryview(network.as_byte_view(out))
            mv[:len(view)] = view
        return 0 if view is None else len(view)

    def scatter_data_into(self, payloads, out) -> int:
        assert payloads is not None and len(payloads) == 1
        view = _as_buffer(payloads[0])
        if out is not None:
            mv = memoryview(network.as_byte_view(out))
            mv[:len(view)] = view
        return len(view)


class TcpCoordinator(Controller):
    """Rank 0: accepts one persistent connection per worker.

    Per-cycle gather/broadcast hot paths go through the native core
    (native/hvdtpu.cc hvd_gather_frames: one poll(2) loop servicing all
    workers with the GIL released) when the library is available; the
    Python per-channel loop is the fallback."""

    def __init__(self, size: int, port: int = 0, secret: bytes = b"",
                 start_timeout: float = 30.0, listener=None,
                 hierarchical: bool = True,
                 heartbeat_interval: float = 5.0,
                 heartbeat_timeout: float = 30.0,
                 elastic_port: Optional[int] = None,
                 world_id: int = 0,
                 tenant_desc: Optional[dict] = None):
        """``listener`` — an already-bound listening socket to adopt
        instead of binding ``port``. Launch layers that must publish
        the coordinator endpoint BEFORE init (Spark rendezvous,
        hvdtpurun's per-host port reservation) hand the bound socket
        over so there is no close-then-rebind window for another
        process to steal the port.

        ``hierarchical`` — allow per-host control-plane aggregation:
        when the world spans multiple hosts with more than one rank
        each, remote leaf ranks migrate to their host's local root
        after the handshake and the coordinator keeps ONE channel per
        remote host, so per-cycle fan-in is n_hosts + local ranks
        instead of world size (the control-plane analog of the
        reference's hierarchical allreduce communicator split,
        reference: operations.cc:729-764, 822-841)."""
        self._secret = secret
        self._server = listener if listener is not None \
            else network.listen(port)
        self.port = self._server.getsockname()[1]
        self._channels: Dict[int, network.Channel] = {}
        self._hostname = _my_hostname()
        self._size = size
        self._start_timeout = start_timeout
        self._hierarchical = hierarchical
        self._hb_interval = heartbeat_interval
        self._hb_timeout = heartbeat_timeout
        self._ping_seq = 0
        self._last_ping = 0.0
        self.topology = None  # set by accept_workers
        self._fanout: Optional[_NativeFanout] = None
        # channel owner rank -> all ranks that channel represents
        # (ascending; owner first). Flat world: every owner maps to
        # itself. Hierarchical: a remote local root carries its host.
        self._members: Dict[int, List[int]] = {}
        self._owner_of: Dict[int, int] = {}
        self._has_aggregates = False
        # owner rank -> monotonic time of its last control frame
        # (maintained only when metrics are attached; feeds the
        # per-peer heartbeat-age gauges).
        self._last_seen: Dict[int, float] = {}
        # Native steady-cycle state (common/steady.py): per-peer
        # scratch arena + the ctypes PING callback, built lazily on
        # the first steady cycle.
        self._steady_scratch = None
        self._steady_on_idle = None
        # Elastic membership (common/elastic.py): this rank's elastic
        # listener port, exchanged in the handshake so every member
        # learns the full rank -> (host, port) re-rendezvous endpoint
        # map. None = elastic off; populated by accept_workers.
        self._elastic_port = elastic_port
        self.elastic_endpoints: Optional[Dict[int, tuple]] = None
        # Tenancy (common/tenancy.py): the world id every member must
        # present in its handshake — a dialer carrying another world's
        # id (a derived-port collision between two concurrent
        # sub-worlds) is refused at accept instead of poisoning this
        # world's gathers. The coordinator's tenant descriptor
        # (weight/quota knobs) broadcasts with the handshake so
        # scheduling state is world-replicated from cycle 0.
        self._world_id = world_id
        self.tenant_desc = tenant_desc

    def accept_workers(self) -> None:
        deadline = time.monotonic() + self._start_timeout
        hostnames = [None] * self._size
        hostnames[0] = self._hostname

        def _validate(hello):
            r = int(hello["rank"])
            if r <= 0 or r >= self._size or r in self._channels:
                raise ConnectionError(f"bad or duplicate rank {r}")
            wid = int(hello.get("world_id", 0))
            if wid != self._world_id:
                raise ConnectionError(
                    f"rank {r} dialed with world id {wid:#010x}; this "
                    f"coordinator serves world {self._world_id:#010x} "
                    f"— two sub-worlds are sharing a port")
            hello["hostname"]  # reject (KeyError) if absent
            return r

        accepts = _accept_handshakes(
            self._server, self._secret, deadline,
            lambda: (f"Only {len(self._channels) + 1}/{self._size} ranks "
                     f"connected within start timeout; increase "
                     f"HOROVOD_START_TIMEOUT if startup is slow."),
            _validate)
        elastic_ports: Dict[int, int] = {}
        peer_ips: Dict[int, str] = {}
        while len(self._channels) < self._size - 1:
            r, hello, ch = next(accepts)
            hostnames[r] = hello["hostname"]
            ch.peer = f"rank {r} ({ch.peer})"
            # hvdlint: owned-by=main -- rendezvous runs before the world's cycle threads start (Thread.start happens-before publishes it); elastic rebuilds a fresh coordinator
            self._channels[r] = ch
            if hello.get("elastic_port") is not None:
                elastic_ports[r] = int(hello["elastic_port"])
                try:
                    peer_ips[r] = ch.sock.getpeername()[0]
                except OSError:
                    peer_ips[r] = "127.0.0.1"
        # Broadcast the full hostname list so every rank derives the same
        # topology (reference: operations.cc:729-764).
        self.topology = compute_topology(0, hostnames)
        topo = self.topology
        # Hierarchy pays only when remote hosts have leaf ranks to fold
        # behind their local root.
        remote_leaves = (self._size - topo.local_sizes[0]
                         - (topo.cross_size - 1))
        hier = (self._hierarchical and topo.cross_size > 1
                and remote_leaves > 0)
        handshake = {"hostnames": hostnames, "hier": hier}
        if self._world_id:
            handshake["world_id"] = self._world_id
        if self.tenant_desc is not None:
            handshake["tenant"] = self.tenant_desc
        # Elastic endpoint map: only meaningful when EVERY member runs
        # elastic mode (the knob must be world-uniform, like the cache
        # knobs); a partial map would leave some ranks unreachable at
        # re-rendezvous, so it is withheld entirely.
        if self._elastic_port is not None \
                and len(elastic_ports) == self._size - 1:
            handshake["elastic"] = {
                "coord_port": self._elastic_port,
                "ports": {str(r): p for r, p in elastic_ports.items()},
                "ips": {str(r): ip for r, ip in peer_ips.items()},
            }
            self.elastic_endpoints = {0: ("", self._elastic_port)}
            for r, p in elastic_ports.items():
                self.elastic_endpoints[r] = (peer_ips[r], p)
        elif self._elastic_port is not None:
            hlog.warning(
                "HOROVOD_ELASTIC is not set on every rank; elastic "
                "re-rendezvous disabled for this world", rank=0)
        blob = json.dumps(handshake).encode()
        for r, ch in self._channels.items():
            ch.send(blob, TAG_HANDSHAKE)
        self._members = {r: [r] for r in self._channels}
        self._peer_ip_override: Dict[int, str] = {}
        if hier:
            self._setup_hierarchy(hostnames, deadline)
        self._owner_of = {}
        for owner, ms in self._members.items():
            for m in ms:
                self._owner_of[m] = owner
        self._has_aggregates = any(
            len(ms) > 1 for ms in self._members.values())
        hb = None
        if self._hb_timeout and self._hb_timeout > 0:
            hb = _hb_normalized(self._hb_timeout, self._hb_interval) \
                + (self._ping_peers,)
            for ch in self._channels.values():
                ch.arm(self._hb_timeout, self._hb_interval,
                       on_idle=self._ping_peers)
        if self._size > 1:
            self._fanout = _NativeFanout.create(self._channels,
                                                self._secret, hb=hb,
                                                on_metrics=self._on_metrics,
                                                on_trace=self._on_trace_frame)
        hlog.debug(f"coordinator up: {self._size} ranks, "
                   f"{self.topology.cross_size} hosts, "
                   f"fan-in {len(self._channels)}", rank=0)

    def _setup_hierarchy(self, hostnames: List[str],
                         deadline: float) -> None:
        """Collapse each remote host's ranks behind its local root:
        gather root listener ports, hand the port map to remote leaves,
        and drop their direct channels. After this the coordinator's
        per-cycle fan-in is (host-0 local ranks) + (remote hosts).
        Every blocking recv here is bounded by the same start deadline
        that bounds accept_workers — a root dying mid-setup must fail
        the job fast, not hang it."""
        _, host_members = host_groups(hostnames)
        root_ports: Dict[str, int] = {}
        for cross, members in enumerate(host_members[1:], start=1):
            if len(members) == 1:
                continue  # solo host: stays a direct channel
            root = members[0]
            tag, data = self._recv_by(self._channels[root], deadline,
                                      f"port report from root {root}")
            if tag != TAG_HANDSHAKE:
                raise ConnectionError(
                    f"expected root port report from rank {root}, got "
                    f"tag {tag}")
            root_ports[str(cross)] = int(
                json.loads(data.decode())["port"])
        map_blob = json.dumps({"roots": root_ports}).encode()
        agg_roots: List[int] = []
        for members in host_members[1:]:
            if len(members) == 1:
                continue
            for leaf in members[1:]:
                ch = self._channels.pop(leaf)
                self._members.pop(leaf)
                ch.send(map_blob, TAG_HANDSHAKE)
                ch.close()
            self._members[members[0]] = members
            agg_roots.append(members[0])
        # Each root reports the IPs it observed its leaves connect
        # from, once they all arrive. A non-loopback leaf IP (per-rank
        # containers, HOROVOD_TPU_LOCAL_ROOT_ADDR set) overrides
        # worker_peer_ip for that rank so ring rendezvous dials the
        # leaf's own address; loopback means shared-netns, where the
        # root channel's IP is the host's reachable address for all
        # its ranks.
        for root in agg_roots:
            tag, data = self._recv_by(self._channels[root], deadline,
                                      f"leaf-IP report from root {root}")
            if tag != TAG_HANDSHAKE:
                raise ConnectionError(
                    f"expected leaf-IP report from rank {root}, got "
                    f"tag {tag}")
            for r, ip in json.loads(data.decode())["leaf_ips"].items():
                if _dialable_leaf_ip(ip):
                    self._peer_ip_override[int(r)] = ip

    @staticmethod
    def _recv_by(ch: network.Channel, deadline: float,
                 what: str) -> tuple:
        """recv() bounded by the startup deadline."""
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"start timeout expired waiting for {what}; increase "
                f"HOROVOD_START_TIMEOUT if startup is slow.")
        ch.sock.settimeout(remaining)
        try:
            return ch.recv()
        except socket.timeout:
            raise TimeoutError(
                f"start timeout expired waiting for {what}; increase "
                f"HOROVOD_START_TIMEOUT if startup is slow.")
        finally:
            ch.sock.settimeout(None)

    def _expand(self, out: List[bytes],
                allow_combined: bool = False) -> List[bytes]:
        """Unpack aggregate frames from local roots into per-rank
        slots (gather direction). ``allow_combined`` (control-plane
        request gathers only): a local root that AND-reduced its whole
        host's cache bitmasks forwards ONE CACHED_AGG cycle frame
        instead of a per-rank pack — it stays in the owner's slot and
        the members' slots are left empty, since the fold already
        accounts for every rank behind it. Request-tag packs that
        could NOT be folded arrive under an explicit PACKED envelope
        byte (a raw pack's leading u32 count is ambiguous: 2 ranks
        pack to a leading 0x02 — the CACHED_AGG kind byte)."""
        if not self._has_aggregates:
            return out
        for owner, members in self._members.items():
            if len(members) == 1:
                continue
            blob = out[owner]
            if allow_combined:
                # A tenant world's folded aggregate leads with the
                # world-id envelope; the CACHED_AGG kind byte then
                # sits right after it (wire.read_world).
                kind_off = 5 if blob[:1] == wire.TENANT_PREFIX else 0
                if blob[kind_off:kind_off + 1] == \
                        wire.CACHED_AGG_PREFIX:
                    for m in members:
                        if m != owner:
                            out[m] = b""
                    continue
                if blob[:1] != wire.PACKED_PREFIX:
                    raise ConnectionError(
                        f"request aggregate from rank {owner} has "
                        f"kind {blob[0] if blob else None}; expected "
                        f"a folded CACHED_AGG frame or a PACKED "
                        f"envelope")
                blob = blob[1:]
            frames = unpack_frames(blob)
            if len(frames) != len(members):
                raise ConnectionError(
                    f"aggregate from rank {owner} carried "
                    f"{len(frames)} frames for {len(members)} ranks")
            for m, f in zip(members, frames):
                out[m] = f
        return out

    def _ping_peers(self) -> None:
        """Fired per idle gather slice: tell every worker the world is
        alive (the straggler the gather waits on is silent TO THEM
        too — without this, their recv deadlines would false-fire on
        a merely slow peer)."""
        _maybe_ping(self, self._channels, 0)

    def _on_metrics(self, r: int, payload: bytes) -> None:
        """A METRICS frame from owner channel ``r`` (native gather or
        the Python recv loop): record liveness and hand it to the
        runtime's aggregator when one is attached."""
        if self._metrics_on:
            self._last_seen[r] = time.monotonic()
        sink = self.metrics_sink
        if sink is not None:
            sink(r, payload)

    def _on_trace_frame(self, r: int, payload: bytes) -> None:
        """A TRACE frame from owner channel ``r``: liveness, then the
        runtime's WorldTraceWriter (dropped without one — a worker
        with tracing armed must never hurt an unarmed coordinator)."""
        if self._metrics_on:
            self._last_seen[r] = time.monotonic()
        sink = self.trace_sink
        if sink is not None:
            sink(r, payload)

    def peer_heartbeat_ages(self) -> Dict[int, float]:
        # list() snapshots the dict atomically under the GIL — the
        # background loop inserts new peers while user threads
        # (hvd.metrics()) iterate.
        now = time.monotonic()
        return {r: now - t for r, t in list(self._last_seen.items())}

    def _recv_ctrl(self, r: int, ch: network.Channel,
                   expect_tag: int) -> bytes:
        """One control frame from rank ``r``'s channel: PINGs are
        liveness-only and skipped, ABORT raises the structured error,
        transport failures are named after the peer."""
        while True:
            try:
                tag, data = ch.recv()
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                raise _abort_error(
                    r, f"control channel to {ch.peer} failed: {e}") \
                    from e
            if tag == TAG_PING:
                continue
            if tag == TAG_METRICS:
                self._on_metrics(r, data)
                continue
            if tag == TAG_TRACE:
                self._on_trace_frame(r, data)
                continue
            if tag == TAG_ABORT:
                origin, cause = heartbeat.decode_abort(data)
                raise _abort_error(origin, cause, resolved=True)
            if tag != expect_tag:
                raise ConnectionError(
                    f"expected tag {expect_tag} from rank {r}, "
                    f"got {tag}")
            if self._metrics_on:
                self._last_seen[r] = time.monotonic()
            return data

    def _raise_transport(self, e: Exception) -> None:
        """Turn an anonymous transport error from a fan-out primitive
        into a WorldAbortedError naming the dead peer when one can be
        identified (native gather and broadcast errors carry no rank)."""
        dead = _dead_peers(self._channels)
        if dead:
            raise _abort_error(
                dead[0], f"connection to rank {dead[0]} lost: {e}") \
                from e
        raise _abort_error(0, f"coordinator transport failure: {e}") \
            from e

    def _gather_frames(self, payload, expect_tag: int) -> List[bytes]:
        """One frame per channel (native poll loop when available),
        rank-indexed with this rank's own payload at 0, aggregate
        frames expanded to their member ranks. Combined (AND-reduced)
        cache bitmask aggregates are only meaningful on the request
        tag — a data-plane payload may begin with any byte."""
        out: List[bytes] = [b""] * self._size
        out[0] = payload
        # Straggler attribution (common/trace.py): stamp per-owner
        # arrival times on request gathers when the runtime armed it.
        # Rank 0's own frame "arrives" at gather start — the baseline
        # every lag is measured against. The native fanout stamps at
        # true frame completion (in C); the Python fallback stamps as
        # its sequential recv loop returns, which is best-effort for
        # frames that were already buffered. The hook is captured ONCE
        # — the trace-overhead toggle bench re-points it from another
        # thread mid-gather, and check-then-recheck would call None.
        on_arrivals = self._on_arrivals
        track = (expect_tag == TAG_REQUESTS
                 and on_arrivals is not None)
        arrivals: Optional[Dict[int, float]] = \
            {0: time.monotonic()} if track else None
        try:
            if self._fanout is not None:
                gathered = self._fanout.gather(expect_tag)
                if track:
                    arrivals.update(self._fanout.last_arrivals)
                if self._metrics_on:
                    now = time.monotonic()
                    rx = 0
                    for r, data in gathered.items():
                        out[r] = data
                        self._last_seen[r] = now
                        rx += len(data)
                    self._m_ctrl_rx.inc(rx)
                else:
                    for r, data in gathered.items():
                        out[r] = data
            else:
                for r, ch in self._channels.items():
                    out[r] = self._recv_ctrl(r, ch, expect_tag)
                    if track:
                        arrivals[r] = time.monotonic()
                if self._metrics_on:
                    self._m_ctrl_rx.inc(sum(
                        len(out[r]) for r in self._channels))
        except WorldAbortedError:
            raise
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)
        if track:
            on_arrivals(arrivals)
        return self._expand(out,
                            allow_combined=(expect_tag == TAG_REQUESTS))

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        return self._gather_frames(payload, TAG_REQUESTS)

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        assert payload is not None
        if self._metrics_on:
            self._m_ctrl_tx.inc(len(payload) * len(self._channels))
        try:
            if self._fanout is not None:
                self._fanout.send_all(payload, TAG_RESPONSES)
                return payload
            for ch in self._channels.values():
                ch.send(payload, TAG_RESPONSES)
            return payload
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)

    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        return self._gather_frames(_as_buffer(payload), TAG_DATA)

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        payload = _as_buffer(payload)
        try:
            if root_rank != 0:
                # Pull the payload up from the root's owning channel,
                # then fan out to every OTHER channel — the owner (the
                # root itself, or the local root relaying for it)
                # already has the bytes and has distributed them on its
                # host, and echoing them back would double its traffic.
                owner = self._owner_of[root_rank]
                payload = self._recv_ctrl(owner, self._channels[owner],
                                          TAG_DATA)
                if self._fanout is not None:
                    self._fanout.send_all(payload, TAG_DATA,
                                          exclude_rank=owner)
                    return payload
                for r, ch in self._channels.items():
                    if r != owner:
                        ch.send(payload, TAG_DATA)
                return payload
            assert payload is not None
            if self._fanout is not None:
                self._fanout.send_all(payload, TAG_DATA)
                return payload
            for ch in self._channels.values():
                ch.send(payload, TAG_DATA)
            return payload
        except WorldAbortedError:
            raise
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        assert payloads is not None and len(payloads) == self._size
        per_owner: Dict[int, bytes] = {
            owner: (_as_buffer(payloads[owner]) if len(ms) == 1
                    else pack_frames([_as_buffer(payloads[m])
                                      for m in ms]))
            for owner, ms in self._members.items()}
        try:
            if self._fanout is not None:
                self._fanout.scatter(per_owner, TAG_DATA)
                return payloads[0]
            for r, ch in self._channels.items():
                ch.send(per_owner[r], TAG_DATA)
            return payloads[0]
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)

    def _recv_data_into(self, r: int, ch: network.Channel, out) -> int:
        """One TAG_DATA frame from rank ``r`` straight into ``out``
        (the recv-into mirror of _recv_ctrl): out-of-band frames are
        absorbed — from the spill when they exceed ``out`` (a METRICS
        or ABORT frame may well be bigger than a small data payload),
        overwritten in place otherwise."""
        view = memoryview(network.as_byte_view(out))
        while True:
            try:
                tag, n, spill = ch.recv_into_spill(view)
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                raise _abort_error(
                    r, f"control channel to {ch.peer} failed: {e}") \
                    from e
            if tag == TAG_PING:
                continue
            if tag == TAG_METRICS:
                self._on_metrics(r, spill if spill is not None
                                 else bytes(view[:n]))
                continue
            if tag == TAG_TRACE:
                self._on_trace_frame(r, spill if spill is not None
                                     else bytes(view[:n]))
                continue
            if tag == TAG_ABORT:
                origin, cause = heartbeat.decode_abort(
                    spill if spill is not None else bytes(view[:n]))
                raise _abort_error(origin, cause, resolved=True)
            if tag != TAG_DATA:
                raise ConnectionError(
                    f"expected tag {TAG_DATA} from rank {r}, got {tag}")
            if spill is not None:
                raise ConnectionError(
                    f"data frame of {n} bytes from rank {r} overflows "
                    f"{len(view)}-byte buffer")
            if self._metrics_on:
                self._last_seen[r] = time.monotonic()
                self._m_ctrl_rx.inc(n)
            return n

    def gather_data_into(self, payload, outs) -> Optional[List[int]]:
        if self._has_aggregates:
            # Hierarchical owners deliver pack_frames aggregates —
            # per-rank payloads interleave inside one frame, so this
            # tier takes the classic gather and one copy per rank.
            gathered = self.gather_data(payload)
            lens = [0] * self._size
            for r in range(1, self._size):
                data = gathered[r]
                mv = memoryview(network.as_byte_view(outs[r]))
                mv[:len(data)] = data
                lens[r] = len(data)
            return lens
        if self._reactor and self._fanout is not None \
                and self._fanout.batched_ok:
            return self._gather_data_into_batched(outs)
        lens = [0] * self._size
        try:
            for r, ch in self._channels.items():
                lens[r] = self._recv_data_into(r, ch, outs[r])
        except WorldAbortedError:
            raise
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)
        return lens

    def _gather_data_into_batched(self, outs) -> List[int]:
        """Reactor data gather: every worker's TAG_DATA frame lands
        straight in its preallocated buffer through ONE batched
        readiness submission per wakeup (_NativeFanout.gather_into)
        instead of N sequential Python recv loops. Wire-identical to
        the sequential path — only this rank's recv scheduling
        changes — so HOROVOD_TPU_REACTOR may differ across ranks."""
        fan = self._fanout
        lens = [0] * self._size
        try:
            got, _arrivals, batches = fan.gather_into(
                TAG_DATA, {r: outs[r] for r in self._channels})
        except WorldAbortedError:
            raise
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)
        for r, n in got.items():
            lens[r] = n
        hist = self._m_reactor_batch
        if hist is not None:
            for b in batches:
                hist.observe(b)
        if self._metrics_on:
            now = time.monotonic()
            for r in got:
                self._last_seen[r] = now
            self._m_ctrl_rx.inc(sum(got.values()))
        return lens

    def broadcast_data_into(self, payload, out,
                            root_rank: int = 0) -> int:
        try:
            if root_rank == 0:
                payload = _as_buffer(payload)
                assert payload is not None
                if self._metrics_on:
                    self._m_ctrl_tx.inc(
                        len(payload) * len(self._channels))
                if self._fanout is not None:
                    self._fanout.send_all(payload, TAG_DATA)
                else:
                    for ch in self._channels.values():
                        ch.send(payload, TAG_DATA)
                return len(payload)
            owner = self._owner_of[root_rank]
            n = self._recv_data_into(owner, self._channels[owner], out)
            view = memoryview(network.as_byte_view(out))[:n]
            if self._fanout is not None:
                self._fanout.send_all(view, TAG_DATA,
                                      exclude_rank=owner)
            else:
                for r, ch in self._channels.items():
                    if r != owner:
                        ch.send(view, TAG_DATA)
            return n
        except WorldAbortedError:
            raise
        except (ConnectionError, OSError) as e:
            self._raise_transport(e)

    def scatter_data_into(self, payloads, out) -> int:
        assert payloads is not None and len(payloads) == self._size
        self.scatter_data(payloads)  # send-only for the coordinator
        return len(_as_buffer(payloads[0]))

    # -- native steady cycle ---------------------------------------------
    def steady_native_ready(self) -> bool:
        if self._has_aggregates or not self._channels:
            return False
        from horovod_tpu import native as _native
        return _native.get() is not None

    def steady_spec_cycle(self, plan, bufs):
        from horovod_tpu import native as _native
        from horovod_tpu.common import steady as _steady
        lib = _native.get()
        if lib is None or self._has_aggregates or not plan.native_ok \
                or not self._channels:
            return None
        ranks = sorted(self._channels)
        fds = []
        for r in ranks:
            try:
                fd = self._channels[r].sock.fileno()
            except OSError:
                fd = -1
            if fd < 0:
                raise _abort_error(
                    r, f"connection to rank {r} lost before the "
                       f"steady cycle")
            fds.append(fd)
        hb = None
        if self._hb_timeout and self._hb_timeout > 0:
            hb = _hb_normalized(self._hb_timeout, self._hb_interval)
            if self._steady_on_idle is None:
                self._steady_on_idle = _native.ON_IDLE_FUNC(
                    self._ping_peers)
        if self._steady_scratch is None:
            from horovod_tpu.common.arena import FusionArena
            self._steady_scratch = FusionArena()

        def on_oob(idx: int, tag: int, payload: bytes) -> bool:
            if tag == TAG_METRICS:
                self._on_metrics(ranks[idx], payload)
                return True
            if tag == TAG_TRACE:
                self._on_trace_frame(ranks[idx], payload)
                return True
            return False

        kind, val = _steady.run_coord_cycle(
            lib, plan, fds, self._secret, bufs, bytes((TAG_PING,)),
            TAG_REQUESTS, TAG_RESPONSES, hb,
            self._steady_on_idle if hb is not None else None,
            self._steady_scratch, on_oob)
        if kind == _steady.DONE:
            segs, arrive = val
            on_arrivals = self._on_arrivals  # one read; see _gather_frames
            if on_arrivals is not None:
                # The native steady gather stamps per-peer arrivals in
                # C (CLOCK_MONOTONIC); 0.0 marks a frame absorbed in a
                # previous resumed slice — skip it rather than invent
                # a lag. Rank 0's own contribution is "already there".
                arrivals = {r: t for r, t in zip(ranks, arrive) if t}
                if arrivals:
                    arrivals[0] = min(arrivals.values())
                    on_arrivals(arrivals)
            if self._metrics_on:
                now = time.monotonic()
                nbytes = plan.payload_nbytes
                for r in ranks:
                    self._last_seen[r] = now
                self._m_ctrl_rx.inc(nbytes * len(ranks))
                self._m_ctrl_tx.inc(nbytes * len(ranks))
            return ("done", segs)
        if kind == _steady.DEV:
            idx, tag, payload, done, peer_views = val
            if tag == TAG_ABORT:
                origin, cause = heartbeat.decode_abort(payload)
                raise _abort_error(origin, cause, resolved=True)
            if tag != TAG_REQUESTS:
                raise ConnectionError(
                    f"expected tag {TAG_REQUESTS} from rank "
                    f"{ranks[idx]}, got {tag}")
            # Classic fallback: rank-indexed frames — absorbed steady
            # frames re-serialize from scratch, the deviant frame rides
            # as-is, everyone still owed delivers classically.
            out = [b""] * self._size
            out[0] = plan.frame_bytes(bufs)
            out[ranks[idx]] = payload
            try:
                for i, r in enumerate(ranks):
                    if done[i]:
                        out[r] = _steady.peer_frame_bytes(
                            plan, peer_views[i])
                    elif i != idx:
                        out[r] = self._recv_ctrl(r, self._channels[r],
                                                 TAG_REQUESTS)
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                self._raise_transport(e)
            return ("fallback", out)
        rc, done = val
        if rc == _steady.ETIMEDOUT:
            waiting = [ranks[i] for i in range(len(ranks))
                       if not done[i]]
            raise _abort_error(
                waiting[0] if waiting else -1,
                f"no control frame from rank(s) {waiting} for "
                f"{self._hb_timeout:g}s — peer presumed dead "
                f"(heartbeat timeout; raise HOROVOD_HEARTBEAT_TIMEOUT "
                f"if peers legitimately stall longer)")
        self._raise_transport(ConnectionError(
            f"native steady cycle failed: errno {-rc}"))

    def worker_peer_ip(self, rank: int) -> str:
        """IP of worker ``rank`` as seen from this coordinator — the
        address other ranks use to reach that worker's data listener
        (ring rendezvous, ops/ring.py). Under the hierarchical control
        plane a shared-netns leaf shares its host's IP, so its local
        root's channel answers for it; a leaf with its own network
        identity (non-loopback connect to its root) reported its real
        IP at setup and that override wins."""
        ip = self._peer_ip_override.get(rank)
        if ip is not None:
            return ip
        return self._channels[self._owner_of[rank]].sock.getpeername()[0]

    def abort(self, origin_rank: int, cause: str) -> None:
        payload = heartbeat.encode_abort(origin_rank, cause)
        for ch in self._channels.values():
            try:
                ch.send(payload, TAG_ABORT)
            except Exception:
                pass  # that peer is already dead/unreachable

    def sever_connection(self, target_rank: Optional[int] = None) -> None:
        if target_rank is not None:
            owner = self._owner_of.get(target_rank, target_rank)
            ch = self._channels.get(owner)
            if ch is not None:
                ch.close()
            return
        for ch in self._channels.values():
            ch.close()

    def drain_abort_notice(self, grace_s: float = 0.0) -> Optional[tuple]:
        return _drain_abort(self._channels, grace_s)

    def close(self) -> None:
        for ch in self._channels.values():
            try:
                ch.close()
            except OSError:
                pass  # stage-guarded: the listener must still close
        self._server.close()


class TcpWorker(Controller):
    """Ranks 1..size-1: one persistent connection upward.

    Flat world: the upward channel goes straight to the coordinator.
    Hierarchical world (coordinator announced ``hier`` in the
    handshake): a remote host's local_rank-0 process becomes the host's
    LOCAL ROOT — it keeps the coordinator channel, accepts loopback
    connections from its host's leaf ranks, and relays every
    control/data primitive between them and the coordinator, packing
    the host's per-rank frames into one aggregate frame upward
    (pack_frames). Remote leaf ranks migrate: they drop the coordinator
    channel and point their upward channel at the local root instead —
    every op below then works unchanged for them. This is the
    control-plane rendering of the reference's LOCAL/CROSS communicator
    split (reference: horovod/common/operations.cc:729-764). The root's
    per-cycle child fan-in/fan-out rides the same native poll(2) hot
    path as the coordinator's (_NativeFanout), so the hierarchy adds a
    hop without adding a Python per-channel loop."""

    def __init__(self, rank: int, size: int, addr: str, port: int,
                 secret: bytes = b"", start_timeout: float = 30.0,
                 heartbeat_interval: float = 5.0,
                 heartbeat_timeout: float = 30.0,
                 elastic_port: Optional[int] = None,
                 world_id: int = 0):
        self.coordinator_addr = addr  # rank 0's reachable address
        self._hb_interval = heartbeat_interval
        self._hb_timeout = heartbeat_timeout
        self._ping_seq = 0
        self._last_ping = 0.0
        self._world_id = world_id
        self._up_rank = 0  # who the upward channel talks to
        self._ch = network.connect(addr, port, secret,
                                   timeout=start_timeout,
                                   retry_deadline=start_timeout)
        self._ch.peer = f"coordinator ({self._ch.peer})"
        hello_d = {"rank": rank, "hostname": _my_hostname()}
        if elastic_port is not None:
            hello_d["elastic_port"] = elastic_port
        if world_id:
            hello_d["world_id"] = world_id
        hello = json.dumps(hello_d).encode()
        self._ch.send(hello, TAG_HANDSHAKE)
        tag, payload = self._ch.recv()
        if tag != TAG_HANDSHAKE:
            raise ConnectionError("handshake failed")
        info = json.loads(payload.decode())
        coord_wid = int(info.get("world_id", 0))
        if coord_wid != world_id:
            raise ConnectionError(
                f"dialed a coordinator serving world {coord_wid:#010x} "
                f"while joining world {world_id:#010x} — two "
                f"sub-worlds are sharing a port (check the derived "
                f"sub-world coordinator ports)")
        # Tenant descriptor broadcast by the coordinator (tenancy.py):
        # the world-replicated scheduling knobs — the coordinator's
        # values win over any rank-local env, like the fusion
        # threshold.
        self.tenant_desc = info.get("tenant")
        hostnames = info["hostnames"]
        # Elastic re-rendezvous endpoint map (rank 0's host is the
        # address this worker dialed — provably reachable from here).
        self.elastic_endpoints: Optional[Dict[int, tuple]] = None
        if info.get("elastic") is not None:
            em = info["elastic"]
            self.elastic_endpoints = {0: (addr, int(em["coord_port"]))}
            for r_s, p in em["ports"].items():
                self.elastic_endpoints[int(r_s)] = \
                    (em["ips"][r_s], int(p))
        self.topology = compute_topology(rank, hostnames)
        # rank -> loopback channel of each local leaf (local roots only)
        self._children: Dict[int, network.Channel] = {}
        self._child_fanout: Optional[_NativeFanout] = None
        self._members: List[int] = [rank]  # this host's ranks, ascending
        # leaf rank -> its latest raw METRICS frame: folded with this
        # root's own snapshot into ONE frame upward (send_metrics) so
        # coordinator metrics fan-in scales with hosts, like CACHED_AGG.
        self._child_metrics: Dict[int, bytes] = {}
        # Accumulated leaf TRACE frames (NOT latest-wins: spans are
        # one-shot deltas — every frame must forward exactly once).
        # Concatenated into this root's own frame by send_trace;
        # bounded so a wedged upward channel cannot grow it forever.
        self._child_trace: List[bytes] = []
        # liveness timestamps for peer_heartbeat_ages (metrics only)
        self._up_seen = time.monotonic()
        self._child_seen: Dict[int, float] = {}
        # Reusable landing buffer for the chunked cut-through relay's
        # bytes-returning legs (lazily sized; frames past its capacity
        # spill to a native malloc for that call only).
        self._relay_buf: Optional[bytearray] = None
        if (info.get("hier") and self.topology.cross_rank != 0
                and self.topology.local_size > 1):
            _, host_members = host_groups(hostnames)
            members = host_members[self.topology.cross_rank]
            if self.topology.local_rank == 0:
                self._become_local_root(members, secret, start_timeout)
            else:
                self._become_leaf(rank, secret, start_timeout)
        hb = None
        if self._hb_timeout and self._hb_timeout > 0:
            hb = _hb_normalized(self._hb_timeout, self._hb_interval) \
                + (self._ping_children,)
            self._ch.arm(self._hb_timeout, self._hb_interval)
            for r, ch in self._children.items():
                ch.arm(self._hb_timeout, self._hb_interval,
                       on_idle=self._ping_children)
        if self._children:
            self._child_fanout = _NativeFanout.create(
                self._children, secret, hb=hb,
                on_metrics=self._on_child_metrics,
                on_trace=self._on_child_trace)

    def _become_local_root(self, members: List[int], secret: bytes,
                           start_timeout: float) -> None:
        """Open a same-host listener, report its port upward, accept
        this host's leaf ranks."""
        srv = network.listen(0, host=_local_root_addr())
        port = srv.getsockname()[1]
        self._ch.send(json.dumps({"port": port}).encode(), TAG_HANDSHAKE)
        expected = set(members[1:])

        def _validate(hello):
            r = int(hello["rank"])
            if r not in expected:
                raise ConnectionError(f"unexpected rank {r}")
            return r

        accepts = _accept_handshakes(
            srv, secret, time.monotonic() + start_timeout,
            lambda: (f"local root {self.rank}: leaves "
                     f"{sorted(expected)} did not connect within start "
                     f"timeout"),
            _validate)
        while expected:
            r, _, ch = next(accepts)
            ch.send(b"{}", TAG_HANDSHAKE)  # accept ack
            ch.peer = f"rank {r} ({ch.peer})"
            # hvdlint: owned-by=main -- rendezvous runs before the world's cycle threads start (Thread.start happens-before publishes it)
            self._children[r] = ch
            expected.discard(r)
        srv.close()
        self._members = members
        # Report the IPs the leaves connected from so the coordinator
        # can answer worker_peer_ip correctly when leaves have their
        # own network identity (non-loopback deployments).
        leaf_ips = {r: ch.sock.getpeername()[0]
                    for r, ch in self._children.items()}
        self._ch.send(json.dumps({"leaf_ips": leaf_ips}).encode(),
                      TAG_HANDSHAKE)

    def _become_leaf(self, rank: int, secret: bytes,
                     start_timeout: float) -> None:
        """Receive the root-port map, then swap the upward channel from
        the coordinator to this host's local root."""
        tag, data = self._ch.recv()
        if tag != TAG_HANDSHAKE:
            raise ConnectionError(
                f"expected root-port map, got tag {tag}")
        ports = json.loads(data.decode())["roots"]
        port = int(ports[str(self.topology.cross_rank)])
        self._ch.close()
        # hvdlint: owned-by=main -- rendezvous channel swap happens before the world's cycle threads start (Thread.start happens-before publishes it)
        self._ch = network.connect(_local_root_addr(), port, secret,
                                   timeout=start_timeout,
                                   retry_deadline=start_timeout)
        root = self.topology.local_roots[self.topology.cross_rank]
        self._up_rank = root
        self._ch.peer = f"local root rank {root} ({self._ch.peer})"
        self._ch.send(json.dumps({"rank": rank}).encode(), TAG_HANDSHAKE)
        tag, _ = self._ch.recv()
        if tag != TAG_HANDSHAKE:
            raise ConnectionError("local root handshake failed")

    # -- per-cycle primitives (relay through _children when present) -----
    def _ping_children(self) -> None:
        """Fired per idle slice of the child gather (a straggler leaf
        must not look dead to its waiting siblings)."""
        if self._children:
            _maybe_ping(self, self._children, self.rank)

    def _on_child_metrics(self, r: int, payload: bytes) -> None:
        """A leaf's METRICS frame: keep only the LATEST per leaf —
        snapshots are totals, so folding the most recent frame from
        each member is exact regardless of drop/reorder."""
        self._child_metrics[r] = payload
        if self._metrics_on:
            self._child_seen[r] = time.monotonic()

    def _on_child_trace(self, r: int, payload: bytes) -> None:
        """A leaf's TRACE frame: ACCUMULATE (spans are deltas, not
        totals) until send_trace folds the batch upward. Past the cap
        the oldest frame is dropped — lossy beats unbounded."""
        if len(self._child_trace) >= 64:
            del self._child_trace[0]
        self._child_trace.append(payload)
        if self._metrics_on:
            self._child_seen[r] = time.monotonic()

    def send_metrics(self, payload: bytes) -> None:
        try:
            if self._child_metrics:
                # drop_incompatible: ONE leaf on skewed code must not
                # silence the root and every healthy sibling forever —
                # its frame is skipped, the rest of the host reports.
                payload = wire.combine_metrics_frames(
                    [payload] + [self._child_metrics[r]
                                 for r in sorted(self._child_metrics)],
                    drop_incompatible=True)
            self._ch.send(payload, TAG_METRICS)
            if self._metrics_on:
                self._m_ctrl_tx.inc(len(payload))
        except Exception:
            pass  # best-effort: the cycle path owns channel errors

    def send_trace(self, payload: bytes) -> None:
        try:
            if self._child_trace:
                batch, self._child_trace = self._child_trace, []
                payload = wire.combine_trace_frames([payload] + batch)
            self._ch.send(payload, TAG_TRACE)
            if self._metrics_on:
                self._m_ctrl_tx.inc(len(payload))
        except Exception:
            pass  # best-effort, like send_metrics

    def peer_heartbeat_ages(self) -> Dict[int, float]:
        if not self._metrics_on:
            # _up_seen/_child_seen are only maintained with metrics
            # attached; reporting the stale __init__ stamp would feed
            # the stall report an ever-growing bogus age for a
            # perfectly healthy upward peer.
            return {}
        now = time.monotonic()
        ages = {self._up_rank: now - self._up_seen}
        for r, t in list(self._child_seen.items()):
            ages[r] = now - t
        return ages

    def _relay_children_safe(self, data, tag: int) -> None:
        """Best-effort PING/ABORT relay downward — never raises (runs
        on liveness/failure paths)."""
        for ch in self._children.values():
            try:
                ch.send(data, tag)
            except Exception:
                pass

    def _recv_up(self, expect_tag: int) -> bytes:
        """One frame from the upward channel. PINGs prove the world is
        alive (forwarded down so leaf deadlines reset too); ABORT
        relays down then raises; silence past the heartbeat deadline
        or a dead socket names the upward peer as the origin."""
        while True:
            try:
                tag, data = self._ch.recv()
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                raise _abort_error(
                    self._up_rank,
                    f"control channel to {self._ch.peer} failed: {e}") \
                    from e
            if self._metrics_on:
                self._up_seen = time.monotonic()
            if tag == TAG_PING:
                if self._trace_on:
                    self._note_ping(data)
                self._relay_children_safe(data, TAG_PING)
                continue
            if tag in (TAG_METRICS, TAG_TRACE):
                continue  # these only flow upward; tolerate strays
            if tag == TAG_ABORT:
                origin, cause = heartbeat.decode_abort(data)
                self._relay_children_safe(data, TAG_ABORT)
                raise _abort_error(origin, cause, resolved=True)
            if tag != expect_tag:
                raise ConnectionError(
                    f"expected tag {expect_tag} from {self._ch.peer}, "
                    f"got {tag}")
            if self._metrics_on:
                self._m_ctrl_rx.inc(len(data))
            return data

    def _note_ping(self, data: bytes) -> None:
        """Clock-sync t2: a coordinator PING's receipt stamp, the
        worker half of the NTP exchange (common/trace.py). Garbled
        pings are liveness regardless — never an error here. Tenant
        workers skip the note entirely (the send-side guard in
        _maybe_ping has this as its receive-side mirror): a TENANT
        coordinator's ping sequence is a different stream, and its
        (sender==0, seq) stamps would overwrite the process-global
        ClockSync's pending echo and poison the DEFAULT world's
        offset table."""
        if self._world_id:
            return
        try:
            sender, seq = heartbeat.decode_ping(data)
        except ValueError:
            return
        from horovod_tpu.common import trace as htrace
        htrace.clock().ping_received(sender, seq, time.monotonic())

    def _recv_child(self, r: int, tag: int) -> bytes:
        while True:
            try:
                t, data = self._children[r].recv()
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                raise _abort_error(
                    r, f"control channel to local rank {r} failed: {e}") \
                    from e
            if t == TAG_METRICS:
                self._on_child_metrics(r, data)
                continue
            if t == TAG_TRACE:
                self._on_child_trace(r, data)
                continue
            if t == TAG_ABORT:
                origin, cause = heartbeat.decode_abort(data)
                raise _abort_error(origin, cause, resolved=True)
            if t != tag:
                raise ConnectionError(
                    f"expected tag {tag} from local rank {r}, got {t}")
            if self._metrics_on:
                self._child_seen[r] = time.monotonic()
            return data

    def _raise_child_transport(self, e: Exception, what: str):
        """Turn an anonymous transport error on the leaf tier into a
        named blame: a probed-dead leaf if there is one, else this
        rank (mirror of TcpCoordinator._raise_transport)."""
        dead = _dead_peers(self._children)
        origin = dead[0] if dead else self.rank
        raise _abort_error(origin, f"{what} failed: {e}") from e

    def _send_children(self, data, tag: int,
                       exclude_rank: Optional[int] = None) -> None:
        try:
            if self._child_fanout is not None:
                self._child_fanout.send_all(data, tag,
                                            exclude_rank=exclude_rank)
                return
            for r, ch in self._children.items():
                if r != exclude_rank:
                    ch.send(data, tag)
        except (ConnectionError, OSError) as e:
            self._raise_child_transport(e, "relay to local leaves")

    def _send_up(self, payload, tag: int) -> None:
        if self._metrics_on:
            self._m_ctrl_tx.inc(len(payload))
        try:
            self._ch.send(payload, tag)
        except (ConnectionError, OSError) as e:
            raise _abort_error(
                self._up_rank,
                f"control channel to {self._ch.peer} failed: {e}") \
                from e

    def _gather_up(self, payload, tag: int) -> None:
        if self._children:
            try:
                if self._child_fanout is not None:
                    frames = self._child_fanout.gather(tag)
                else:
                    frames = {r: self._recv_child(r, tag)
                              for r in self._children}
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                self._raise_child_transport(e, "gather from local leaves")
            frames[self.rank] = payload
            ordered = [frames[r] for r in self._members]
            payload = None
            if tag == TAG_REQUESTS:
                # Steady-state fast path: when the whole host sent
                # cache bitmask frames, AND/OR-fold them here and
                # forward ONE mask for the host — the coordinator's
                # per-cycle bytes then scale with n_hosts, not ranks.
                # Unfoldable mixes get an explicit PACKED envelope so
                # the coordinator can tell a per-rank pack from a
                # folded frame without sniffing ambiguous bytes (a
                # raw pack_frames blob starts with its u32 count —
                # 2 for a 2-rank host, which IS the CACHED_AGG kind).
                payload = wire.combine_cycle_requests(ordered)
                if payload is None:
                    payload = wire.PACKED_PREFIX + pack_frames(ordered)
            if payload is None:
                payload = pack_frames(ordered)
        self._send_up(payload, tag)

    def gather_requests(self, payload: bytes) -> Optional[List[bytes]]:
        self._gather_up(payload, TAG_REQUESTS)
        return None

    def broadcast_responses(self, payload: Optional[bytes]) -> bytes:
        if self._relay_native_ok():
            return self._relay_up_to_children(TAG_RESPONSES)[1]
        data = self._recv_up(TAG_RESPONSES)
        self._send_children(data, TAG_RESPONSES)
        return data

    def gather_data(self, payload: bytes) -> Optional[List[bytes]]:
        self._gather_up(_as_buffer(payload), TAG_DATA)
        return None

    def broadcast_data(self, payload: Optional[bytes],
                       root_rank: int = 0) -> bytes:
        payload = _as_buffer(payload)
        if payload is not None and self.rank == root_rank:
            # Root sends up; the coordinator fans out to the other
            # channels only — our own copy is already authoritative,
            # and our local leaves get it straight from us.
            self._send_up(payload, TAG_DATA)
            self._send_children(payload, TAG_DATA)
            return payload
        if root_rank in self._children:
            # The root is one of our leaves: relay its payload upward
            # and to its local siblings; the coordinator serves the
            # rest of the world and skips this whole host.
            data = self._recv_child(root_rank, TAG_DATA)
            self._send_up(data, TAG_DATA)
            self._send_children(data, TAG_DATA, exclude_rank=root_rank)
            return data
        if self._relay_native_ok():
            return self._relay_up_to_children(TAG_DATA)[1]
        data = self._recv_up(TAG_DATA)
        self._send_children(data, TAG_DATA)
        return data

    def scatter_data(self, payloads: Optional[List[bytes]]) -> bytes:
        data = self._recv_up(TAG_DATA)
        if self._children:
            frames = unpack_frames(data)
            mine: Optional[bytes] = None
            per_child: Dict[int, bytes] = {}
            for r, f in zip(self._members, frames):
                if r == self.rank:
                    mine = f
                else:
                    per_child[r] = f
            try:
                if self._child_fanout is not None:
                    self._child_fanout.scatter(per_child, TAG_DATA)
                else:
                    for r, f in per_child.items():
                        self._children[r].send(f, TAG_DATA)
            except (ConnectionError, OSError) as e:
                self._raise_child_transport(e, "scatter to local leaves")
            assert mine is not None
            return mine
        return data

    def _recv_up_into(self, out, expect_tag: int) -> int:
        """Recv-into mirror of _recv_up: the payload lands straight in
        ``out``; PINGs relay downward and ABORT raises, exactly like
        the bytes path. Out-of-band frames bigger than ``out`` arrive
        via the spill (a PING can exceed a 0-byte scatter slice), so
        liveness and abort semantics hold at ANY destination size."""
        view = memoryview(network.as_byte_view(out))
        while True:
            try:
                tag, n, spill = self._ch.recv_into_spill(view)
            except WorldAbortedError:
                raise
            except (ConnectionError, OSError) as e:
                raise _abort_error(
                    self._up_rank,
                    f"control channel to {self._ch.peer} failed: {e}") \
                    from e
            if self._metrics_on:
                self._up_seen = time.monotonic()
            if tag == TAG_PING:
                data = spill if spill is not None else bytes(view[:n])
                if self._trace_on:
                    self._note_ping(data)
                self._relay_children_safe(data, TAG_PING)
                continue
            if tag in (TAG_METRICS, TAG_TRACE):
                continue  # these only flow upward; tolerate strays
            if tag == TAG_ABORT:
                data = spill if spill is not None else bytes(view[:n])
                origin, cause = heartbeat.decode_abort(data)
                self._relay_children_safe(data, TAG_ABORT)
                raise _abort_error(origin, cause, resolved=True)
            if tag != expect_tag:
                raise ConnectionError(
                    f"expected tag {expect_tag} from {self._ch.peer}, "
                    f"got {tag}")
            if spill is not None:
                raise ConnectionError(
                    f"frame of {n} bytes from {self._ch.peer} "
                    f"overflows {len(view)}-byte buffer")
            if self._metrics_on:
                self._m_ctrl_rx.inc(n)
            return n

    # -- chunked cut-through relay (docs/performance.md Layer 6) ---------
    def _relay_native_ok(self) -> bool:
        """The cast-while-receiving relay leg is available: reactor on
        for this rank, leaves to serve, and a native core exporting
        hvd_relay_frame (a stale pre-reactor .so keeps the
        store-and-forward path — the wire is identical either way)."""
        if not (self._reactor and self._children):
            return False
        from horovod_tpu import native as _native
        lib = _native.get()
        return lib is not None and hasattr(lib, "hvd_relay_frame")

    def _relay_up_to_children(self, expect_tag: int, out=None):
        """One upward frame relayed to every leaf cast-while-receiving
        (hvd_relay_frame): header + digest go downstream before the
        first payload byte, then each _RELAY_CHUNK_BYTES chunk forwards
        as it arrives — replacing the recv-whole-frame-then-send
        store-and-forward of _recv_up + _send_children with a
        cut-through pipeline, wire byte-identical. METRICS/TRACE strays
        are dropped in C (same tolerance as _recv_up); PING and ABORT
        bounce back here so liveness relays downward and abort decode
        keep their exact sequential semantics. Returns ``(nbytes,
        payload)`` — payload is bytes when ``out`` is None, else None
        with the frame landed in ``out``."""
        import ctypes as ct
        from horovod_tpu import native as _native
        lib = _native.get()
        if out is not None:
            mv = memoryview(network.as_byte_view(out))
        else:
            if self._relay_buf is None:
                self._relay_buf = bytearray(1 << 20)
            mv = memoryview(self._relay_buf)
        win = (ct.c_uint8 * len(mv)).from_buffer(mv) if len(mv) \
            else (ct.c_uint8 * 1)()
        kids = sorted(self._children)
        child_fds = (ct.c_int * len(kids))(
            *[self._children[r].sock.fileno() for r in kids])
        try:
            up_fd = self._ch.sock.fileno()
        except OSError:
            up_fd = -1
        if up_fd < 0:
            raise _abort_error(
                self._up_rank,
                f"control channel to {self._ch.peer} closed before "
                f"the relay")
        secret = self._ch.secret or b""
        sbuf = (ct.c_uint8 * max(1, len(secret))).from_buffer_copy(
            secret or b"\x00")
        skip = (ct.c_uint8 * 2)(TAG_METRICS, TAG_TRACE)
        if self._hb_timeout and self._hb_timeout > 0:
            t_s, i_s = _hb_normalized(self._hb_timeout,
                                      self._hb_interval)
            timeout_ms = max(1, int(t_s * 1000))
            interval_ms = max(1, int(i_s * 1000))
        else:
            timeout_ms = interval_ms = -1
        out_len = ct.c_int64(0)
        out_tag = ct.c_uint8(0)
        spill = ct.POINTER(ct.c_uint8)()
        while True:
            rc = lib.hvd_relay_frame(
                up_fd, child_fds, len(kids), expect_tag,
                ct.addressof(win), len(mv), sbuf, len(secret),
                skip, 2, _RELAY_CHUNK_BYTES, timeout_ms, interval_ms,
                ct.byref(out_len), ct.byref(out_tag), ct.byref(spill))
            if rc == 2:
                # Deviation: an authenticated non-stray frame that is
                # NOT the expected one — it was absorbed, not relayed.
                tag = out_tag.value
                if spill:
                    payload = ct.string_at(spill, out_len.value)
                    lib.hvd_free(spill)
                    spill = ct.POINTER(ct.c_uint8)()
                else:
                    payload = b""
                if self._metrics_on:
                    self._up_seen = time.monotonic()
                if tag == TAG_PING:
                    if self._trace_on:
                        self._note_ping(payload)
                    self._relay_children_safe(payload, TAG_PING)
                    continue
                if tag == TAG_ABORT:
                    origin, cause = heartbeat.decode_abort(payload)
                    self._relay_children_safe(payload, TAG_ABORT)
                    raise _abort_error(origin, cause, resolved=True)
                raise ConnectionError(
                    f"expected tag {expect_tag} from {self._ch.peer}, "
                    f"got {tag}")
            if rc == 1:
                # Expected frame, relayed, but bigger than the landing
                # buffer: the payload rode through a native spill.
                n = out_len.value
                payload = ct.string_at(spill, n) if spill else b""
                if spill:
                    lib.hvd_free(spill)
                    spill = ct.POINTER(ct.c_uint8)()
                if out is not None:
                    raise ConnectionError(
                        f"frame of {n} bytes from {self._ch.peer} "
                        f"overflows {len(mv)}-byte buffer")
                if self._metrics_on:
                    self._up_seen = time.monotonic()
                    self._m_ctrl_rx.inc(n)
                return n, payload
            if rc == 0:
                n = out_len.value
                if self._metrics_on:
                    self._up_seen = time.monotonic()
                    self._m_ctrl_rx.inc(n)
                return n, (bytes(mv[:n]) if out is None else None)
            if rc == -errno.ETIMEDOUT:
                raise _abort_error(
                    self._up_rank,
                    f"no data from {self._ch.peer} for "
                    f"{self._hb_timeout:g}s — peer presumed dead "
                    f"(heartbeat timeout; raise "
                    f"HOROVOD_HEARTBEAT_TIMEOUT if peers legitimately "
                    f"stall longer)")
            # A child write failure surfaces with the same negative rc
            # as an upward read failure — probe the leaves to blame
            # the right tier (mirror of _raise_child_transport).
            dead = _dead_peers(self._children)
            if dead:
                raise _abort_error(
                    dead[0],
                    f"relay to local leaves failed: errno {-rc}")
            raise _abort_error(
                self._up_rank,
                f"control channel to {self._ch.peer} failed during "
                f"the chunked relay: errno {-rc}")

    def gather_data_into(self, payload, outs) -> Optional[List[int]]:
        self._gather_up(_as_buffer(payload), TAG_DATA)
        return None

    def broadcast_data_into(self, payload, out,
                            root_rank: int = 0) -> int:
        if payload is not None and self.rank == root_rank:
            payload = _as_buffer(payload)
            self._send_up(payload, TAG_DATA)
            self._send_children(payload, TAG_DATA)
            return len(payload)
        if root_rank in self._children:
            data = self._recv_child(root_rank, TAG_DATA)
            self._send_up(data, TAG_DATA)
            self._send_children(data, TAG_DATA, exclude_rank=root_rank)
            mv = memoryview(network.as_byte_view(out))
            mv[:len(data)] = data
            return len(data)
        if self._relay_native_ok():
            return self._relay_up_to_children(TAG_DATA, out=out)[0]
        n = self._recv_up_into(out, TAG_DATA)
        if self._children:
            self._send_children(
                memoryview(network.as_byte_view(out))[:n], TAG_DATA)
        return n

    def scatter_data_into(self, payloads, out) -> int:
        if self._children:
            # A local root must unpack the aggregate to relay each
            # leaf's slice — the classic path with one copy out.
            data = self.scatter_data(payloads)
            mv = memoryview(network.as_byte_view(out))
            mv[:len(data)] = data
            return len(data)
        return self._recv_up_into(out, TAG_DATA)

    # -- native steady cycle ---------------------------------------------
    def steady_native_ready(self) -> bool:
        if self._children:
            return False
        from horovod_tpu import native as _native
        return _native.get() is not None

    def steady_spec_cycle(self, plan, bufs):
        from horovod_tpu import native as _native
        from horovod_tpu.common import steady as _steady
        lib = _native.get()
        if lib is None or self._children or not plan.native_ok:
            return None
        try:
            fd = self._ch.sock.fileno()
        except OSError:
            fd = -1
        if fd < 0:
            raise _abort_error(
                self._up_rank,
                f"control channel to {self._ch.peer} closed before "
                f"the steady cycle")
        kind, val = _steady.run_worker_cycle(
            lib, plan, fd, self._ch.secret, bufs,
            bytes((TAG_PING, TAG_METRICS, TAG_TRACE)), TAG_REQUESTS,
            TAG_RESPONSES, self._ch._hb)
        if self._metrics_on:
            self._up_seen = time.monotonic()
        if kind == _steady.DONE:
            if self._metrics_on:
                self._m_ctrl_tx.inc(plan.payload_nbytes)
                self._m_ctrl_rx.inc(plan.payload_nbytes)
            return ("done", val)
        if kind == _steady.FRAME:
            tag, payload = val
            if tag == TAG_ABORT:
                origin, cause = heartbeat.decode_abort(payload)
                raise _abort_error(origin, cause, resolved=True)
            if tag != TAG_RESPONSES:
                raise ConnectionError(
                    f"expected tag {TAG_RESPONSES} from "
                    f"{self._ch.peer}, got {tag}")
            if self._metrics_on:
                self._m_ctrl_rx.inc(len(payload))
            return ("frame", payload)
        rc = val
        if rc == _steady.ETIMEDOUT:
            raise _abort_error(
                self._up_rank,
                f"no data from {self._ch.peer} for "
                f"{self._hb_timeout:g}s — peer presumed dead "
                f"(heartbeat timeout; raise HOROVOD_HEARTBEAT_TIMEOUT "
                f"if peers legitimately stall longer)")
        raise _abort_error(
            self._up_rank,
            f"control channel to {self._ch.peer} failed during the "
            f"steady cycle: errno {-rc}")

    def abort(self, origin_rank: int, cause: str) -> None:
        payload = heartbeat.encode_abort(origin_rank, cause)
        try:
            self._ch.send(payload, TAG_ABORT)  # escalate up
        except Exception:
            pass
        self._relay_children_safe(payload, TAG_ABORT)

    def sever_connection(self, target_rank: Optional[int] = None) -> None:
        if target_rank is not None and target_rank in self._children:
            self._children[target_rank].close()
            return
        self._ch.close()

    def drain_abort_notice(self, grace_s: float = 0.0) -> Optional[tuple]:
        return _drain_abort({self._up_rank: self._ch, **self._children},
                            grace_s)

    def close(self) -> None:
        for ch in self._children.values():
            try:
                ch.close()
            except OSError:
                pass  # stage-guarded: the upward channel must still close
        self._ch.close()
